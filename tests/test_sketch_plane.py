"""Per-window device sketch plane (ISSUE 8) — window semantics, shed
coverage, K-ring equivalence, sharded merge, and the querier e2e.

The exact-path tests pin the plane against per-window numpy oracles
(true distinct counts / frequencies recomputed from the input rows);
the shed tests pin the tentpole's point: a stash too small for the key
space loses exact rows but the window's sketch answers stay in-bound.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from deepflow_tpu.aggregator.sketchplane import SketchConfig
from deepflow_tpu.aggregator.window import WindowConfig, WindowManager
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ops.histogram import LogHistSpec

SK = SketchConfig(
    num_groups=4, hll_precision=8, cms_depth=3, cms_width=512,
    hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.2),
    topk_rows=2, topk_cols=128, pending=10,
)


def _wm(capacity=1 << 11, delay=2, stats_ring=1, sketch=SK):
    return WindowManager(
        WindowConfig(capacity=capacity, delay=delay, stats_ring=stats_ring,
                     sketch=sketch)
    )


def _doc_batch(keys: np.ndarray, t: int, byte_w=100.0, rtt=None):
    """Raw doc rows for WindowManager.ingest keyed by small int ids:
    ip0_w3 carries the key (client identity == flow identity here, so
    distinct clients == distinct keys in the oracle)."""
    n = len(keys)
    keys = np.asarray(keys, np.uint32)
    tags = np.zeros((TAG_SCHEMA.num_fields, n), np.uint32)
    tags[TAG_SCHEMA.index("ip0_w3")] = keys
    tags[TAG_SCHEMA.index("server_port")] = 443
    tags[TAG_SCHEMA.index("protocol")] = 6
    tags[TAG_SCHEMA.index("l3_epc_id1")] = keys % 5
    meters = np.zeros((FLOW_METER.num_fields, n), np.float32)
    meters[FLOW_METER.index("byte_tx")] = byte_w
    meters[FLOW_METER.index("rtt_sum")] = (
        rtt if rtt is not None else np.full(n, 10.0, np.float32)
    )
    meters[FLOW_METER.index("rtt_count")] = 1.0
    ts = np.full(n, t, np.uint32)
    # caller-side doc fingerprint — any injective map of the key works
    hi = keys * np.uint32(2654435761) + np.uint32(1)
    lo = keys ^ np.uint32(0x9E3779B9)
    return (ts, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(tags),
            jnp.asarray(meters), jnp.ones(n, bool))


T0 = 1_700_000_000


def _run(wm, batches):
    """[(keys, t)] → flushed windows (incl. flush_all)."""
    out = []
    for keys, t in batches:
        out.extend(wm.ingest(*_doc_batch(keys, t)))
    out.extend(wm.flush_all())
    return out


def test_per_window_blocks_match_numpy_oracle():
    rng = np.random.default_rng(50)
    per_window = {t: rng.integers(0, 300, 400).astype(np.uint32)
                  for t in (T0, T0 + 1, T0 + 2)}
    wm = _wm()
    flushed = _run(wm, [(k, t) for t, k in per_window.items()])
    assert [f.window_idx for f in flushed] == sorted(per_window)
    for f in flushed:
        blk = f.sketches
        assert blk is not None and blk.window == f.window_idx
        keys = per_window[f.window_idx]
        true_distinct = len(np.unique(keys))
        assert blk.n_updates == len(keys)
        # HLL in-envelope (p=8 → ~6.5% stderr; seeded draw well inside 15%)
        assert abs(blk.distinct() - true_distinct) / true_distinct < 0.15
        # exact rows agree (no shed at this capacity): block and stash
        # describe the same window
        assert f.count == true_distinct
        # CMS overestimate-only against true per-key counts, keyed by
        # the SAME fingerprint the exact rows carry
        uniq, counts = np.unique(keys, return_counts=True)
        hi = uniq * np.uint32(2654435761) + np.uint32(1)
        lo = uniq ^ np.uint32(0x9E3779B9)
        est = blk.estimate(hi, lo)
        true_bytes = counts * 100
        assert (est >= true_bytes).all()
        assert (est <= true_bytes * 1.5 + 500).all()
        # top-K inversion recovers the window's heaviest keys
        top = blk.topk(5)
        heavy_true = set(uniq[np.argsort(-counts)][:3].tolist())
        heavy_rec = {t_["id_a"] for t_ in top}
        assert len(heavy_true & heavy_rec) >= 2
        # latency quantile from the t-digest export path
        assert abs(blk.quantile(0.5) - 10.0) / 10.0 < 0.25


def test_shed_degrades_detail_not_coverage():
    """THE tentpole acceptance shape: a stash far smaller than the key
    space sheds exact rows, but the closed window's sketch block still
    answers distinct-count / frequency / top-K in-bound."""
    rng = np.random.default_rng(51)
    n_keys = 3000
    keys = np.concatenate([
        rng.permutation(n_keys).astype(np.uint32),  # uniform scan
        np.repeat(np.arange(8, dtype=np.uint32), 200),  # planted heavies
    ])
    rng.shuffle(keys)
    # finer HLL than the shared config: p=11 puts 3k keys in the
    # linear-counting regime (error ≪ 1%), the production-shaped knob
    sk = SketchConfig(
        num_groups=4, hll_precision=11, cms_depth=3, cms_width=512,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.2),
        topk_rows=2, topk_cols=128, pending=10,
    )
    wm = _wm(capacity=256, sketch=sk)  # stash holds <10% of the key space
    flushed = _run(wm, [(keys, T0), (keys[:64], T0 + 4)])
    f = flushed[0]
    assert f.window_idx == T0
    # the exact tier shed: far fewer rows than distinct keys...
    assert f.count <= 256 < n_keys
    assert int(np.asarray(wm.state.dropped_overflow)) > 0
    blk = f.sketches
    assert blk is not None
    # ...but sketch coverage is total: every row reached the plane
    assert blk.n_updates == len(keys)
    true_distinct = len(np.unique(keys))
    assert abs(blk.distinct() - true_distinct) / true_distinct < 0.1
    # planted heavy hitters all recovered, in order of weight
    top = blk.topk(8)
    assert {t["id_a"] for t in top} == set(range(8))


def test_stats_ring_blocks_bit_exact_vs_per_batch():
    """K-ring mode (stats_ring=4) defers host syncs; flushed sketch
    blocks must stay BIT-EXACT vs per-batch fetching — same pin the
    exact rows already have (tests/test_feeder.py)."""
    rng = np.random.default_rng(52)
    batches = [(rng.integers(0, 200, 256).astype(np.uint32), t)
               for t in (T0, T0, T0 + 1, T0 + 3, T0 + 4, T0 + 4, T0 + 7)]
    outs = {}
    for k in (1, 4):
        wm = _wm(stats_ring=k)
        outs[k] = _run(wm, [(np.array(ks, np.uint32), t) for ks, t in batches])
    assert [f.window_idx for f in outs[1]] == [f.window_idx for f in outs[4]]
    for a, b in zip(outs[1], outs[4]):
        assert a.count == b.count
        np.testing.assert_array_equal(a.key_hi, b.key_hi)
        if a.sketches is None:
            assert b.sketches is None
            continue
        assert b.sketches is not None
        assert a.sketches.n_updates == b.sketches.n_updates
        np.testing.assert_array_equal(a.sketches.hll, b.sketches.hll)
        np.testing.assert_array_equal(a.sketches.cms, b.sketches.cms)
        np.testing.assert_array_equal(a.sketches.hist, b.sketches.hist)
        np.testing.assert_array_equal(a.sketches.tk_votes, b.sketches.tk_votes)
        np.testing.assert_array_equal(a.sketches.tk_hi, b.sketches.tk_hi)


def test_giant_jump_mid_rows_are_counted_shed():
    """One batch spanning far more than R windows below its own close
    bound: the mid-gap rows cannot each get a ring slot — they must be
    COUNTED out of the sketch tier (CB_SKETCH_SHED), never silently
    merged into a neighbour window, and the exact stash still takes
    them."""
    wm = _wm()
    # open the span
    list(wm.ingest(*_doc_batch(np.arange(10, dtype=np.uint32), T0)))
    # one batch scattered over 40 windows, newest 40 windows ahead:
    # windows below close_w but ≥ R past the base lose sketch coverage
    n = 200
    ts = np.repeat(np.arange(T0, T0 + 40, dtype=np.uint32), 5)
    keys = np.arange(n, dtype=np.uint32)
    b = list(_doc_batch(keys, T0))
    b[0] = ts
    flushed = list(wm.ingest(*b))
    flushed += wm.flush_all()
    c = wm.get_counters()
    assert c["sketch_shed"] > 0
    # exact tier unaffected by the sketch shed: every (window, key)
    # row flushed — batch 1 contributes keys 0..9 at T0, batch 2's
    # window-T0 rows (keys 0..4) merge into them, the rest are unique
    assert sum(f.count for f in flushed) == 10 + (40 - 1) * 5
    # windows that DID get slots carry blocks; shed windows may be bare
    assert any(f.sketches is not None for f in flushed)


def test_pipeline_flow_path_blocks_and_cb_lane():
    """L4Pipeline with the plane on: blocks surface through
    pop_closed_sketches, the CB v4 lane proves updates ran in the fused
    dispatch, and the fused step never retraces."""
    from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
    from deepflow_tpu.datamodel.batch import FlowBatch
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    pipe = L4Pipeline(
        PipelineConfig(window=WindowConfig(capacity=1 << 12, sketch=SK),
                       batch_size=256)
    )
    gen = SyntheticFlowGen(num_tuples=150, seed=53)
    for i, t in enumerate((T0, T0 + 1, T0 + 2, T0 + 5, T0 + 6)):
        pipe.ingest(FlowBatch.from_records(gen.records(128, t)))
    pipe.drain()
    blocks = pipe.pop_closed_sketches()
    assert len(blocks) >= 4
    assert all(b.n_updates > 0 for b in blocks)
    c = pipe.get_counters()
    assert c["sketch_rows"] > 0, "CB_SKETCH_ROWS lane never moved"
    assert c["sketch_shed"] == 0
    assert c["jit_retraces"] == 0


def test_sharded_plane_merges_to_single_device_truth():
    """Cross-mesh merge-on-close: the host-merged per-window block of a
    2-device run equals the 1-device run on the same batch for every
    order-independent lane (register max / integer counter add)."""
    from deepflow_tpu.ingest.replay import SyntheticFlowGen
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=8, hll_precision=7,
        cms_depth=2, cms_width=256,
        hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
        topk_cols=64, sketch_pending=8,
    )
    gen = SyntheticFlowGen(num_tuples=300, seed=54)
    batches = [gen.flow_batch(128, t) for t in (T0, T0 + 1, T0 + 4)]
    blocks = {}
    for n_dev in (1, 2):
        wm = ShardedWindowManager(ShardedPipeline(make_mesh(n_dev), cfg))
        for fb in batches:
            wm.ingest(fb.tags, fb.meters, fb.valid)
        wm.drain()
        blocks[n_dev] = {b.window: b for b in wm.pop_closed_sketches()}
        assert wm.get_counters()["sketch_blocks_closed"] >= 3
    assert set(blocks[1]) == set(blocks[2])
    for w, a in blocks[1].items():
        b = blocks[2][w]
        assert a.n_updates == b.n_updates
        np.testing.assert_array_equal(a.hll, b.hll)
        np.testing.assert_array_equal(a.cms, b.cms)
        np.testing.assert_array_equal(a.hist, b.hist)
        # top-K bucket state is shard-dependent; the recovered heavy
        # set must still overlap strongly
        top_a = {t["key_hi"] for t in a.topk(5)}
        top_b = {t["key_hi"] for t in b.topk(5)}
        assert len(top_a & top_b) >= 3


def test_querier_e2e_sql_and_promql_over_shed_window():
    """Acceptance e2e: high-cardinality traffic into a stash that
    sheds; SQL and PromQL both answer distinct-count, quantile and
    top-K for the closed window from the sketch tier — no exact-row
    dependence."""
    from deepflow_tpu.integration.dfstats import (
        DEEPFLOW_SYSTEM_DB,
        DEEPFLOW_SYSTEM_TABLE,
        SKETCH_METRIC_DISTINCT,
        SKETCH_METRIC_QUANTILE,
        SKETCH_METRIC_TOPK,
        sketch_system_sink,
    )
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.querier.promql import query_instant
    from deepflow_tpu.storage.store import ColumnarStore

    rng = np.random.default_rng(55)
    n_keys = 2000
    keys = np.concatenate([
        rng.permutation(n_keys).astype(np.uint32),
        np.repeat(np.arange(5, dtype=np.uint32), 300),
    ])
    rng.shuffle(keys)
    wm = _wm(capacity=128)  # guaranteed shed
    flushed = _run(wm, [(keys, T0)])
    blocks = [f.sketches for f in flushed if f.sketches is not None]
    assert blocks and int(np.asarray(wm.state.dropped_overflow)) > 0

    store = ColumnarStore()
    sketch_system_sink(store, interval=wm.config.interval)(blocks)

    # SQL: window-level distinct count from the sketch tier
    eng = QueryEngine(store)
    res = eng.execute(
        "SELECT value FROM deepflow_system.deepflow_system WHERE "
        f"metric = '{SKETCH_METRIC_DISTINCT}' AND labels = 'service=all' "
        f"AND time = {T0}"
    )
    assert res.rows == 1
    true_distinct = len(np.unique(keys))
    got = float(res.values["value"][0])
    assert abs(got - true_distinct) / true_distinct < 0.1
    # SQL: quantile rows exist per active service
    res_q = eng.execute(
        "SELECT value FROM deepflow_system.deepflow_system WHERE "
        f"metric = '{SKETCH_METRIC_QUANTILE}'"
    )
    assert res_q.rows > 0 and (res_q.values["value"] > 0).all()
    # SQL: top-K lane, ranked
    res_t = eng.execute(
        "SELECT value FROM deepflow_system.deepflow_system WHERE "
        f"metric = '{SKETCH_METRIC_TOPK}' ORDER BY value DESC LIMIT 5"
    )
    assert res_t.rows == 5

    # PromQL: instant distinct + the topk() surface
    inst = query_instant(
        store, SKETCH_METRIC_DISTINCT + '{service="all"}', T0,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE,
    )
    assert len(inst) == 1
    assert abs(inst[0]["value"] - true_distinct) / true_distinct < 0.1
    top = query_instant(
        store, f"topk(5, {SKETCH_METRIC_TOPK})", T0,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE,
    )
    assert len(top) == 5
    vals = [r["value"] for r in top]
    assert vals == sorted(vals, reverse=True)
    # the planted heavies dominate the recovered ranking
    heavy_ips = {r["labels"]["ip"] for r in top}
    assert heavy_ips <= {str(i) for i in range(5)}


def test_sketchless_manager_unchanged():
    """sketch=None keeps the exact-only contract: 2-tuple flush
    entries, no sketch state, no new lanes moving."""
    wm = _wm(sketch=None)
    flushed = _run(wm, [(np.arange(50, dtype=np.uint32), T0),
                        (np.arange(50, dtype=np.uint32), T0 + 4)])
    assert wm.sk is None
    assert all(f.sketches is None for f in flushed)
    c = wm.get_counters()
    assert c["sketch_rows"] == 0 and c["sketch_shed"] == 0


def test_make_ingest_step_sketch_variant():
    """The bench-facing make_ingest_step(sketch_config=...) signature:
    append carries the plane through the same traced step and claims
    per-window ring slots."""
    import jax

    from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig
    from deepflow_tpu.aggregator.pipeline import make_ingest_step
    from deepflow_tpu.aggregator.sketchplane import sketch_init
    from deepflow_tpu.aggregator.stash import accum_init, stash_init
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    append, fold = make_ingest_step(
        FanoutConfig(), interval=1, sketch_config=SK, delay=2
    )
    append = jax.jit(append, donate_argnums=(0, 1, 3))
    gen = SyntheticFlowGen(num_tuples=100, seed=70)
    fb = gen.flow_batch(128, T0)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    stash = stash_init(1 << 10, TAG_SCHEMA, FLOW_METER)
    acc = accum_init(2 * FANOUT_LANES * 128, TAG_SCHEMA, FLOW_METER)
    sk = sketch_init(SK, 4)
    stash, acc, sk = append(
        stash, acc, jnp.int32(0), sk, tags, jnp.asarray(fb.meters),
        jnp.asarray(fb.valid), jnp.uint32(0),
    )
    assert int(np.asarray(sk.rows)) == int(fb.valid.sum())
    assert (np.asarray(sk.win) != 0xFFFFFFFF).sum() >= 1  # slot claimed


def test_sketch_sink_skips_quantiles_without_latency_samples():
    """Review pin: a service with HLL activity but an all-zero latency
    histogram (UDP-only traffic, rtt_count=0) must produce NO quantile
    series — a fake 0 ms row is indistinguishable from real zero
    latency."""
    from deepflow_tpu.aggregator.sketchplane import WindowSketchBlock
    from deepflow_tpu.integration.dfstats import (
        SKETCH_METRIC_QUANTILE,
        sketch_block_rows,
    )

    g, m = SK.num_groups, SK.hll_m
    hll = np.zeros((g, m), np.int32)
    hll[0, 3] = 4  # service 0 saw clients...
    hll[1, 7] = 2  # ...service 1 too
    hist = np.zeros((g, SK.hist.bins), np.int64)
    hist[1, 5] = 9  # ...but only service 1 has latency samples
    blk = WindowSketchBlock(
        window=T0, config=SK, n_updates=13, hll=hll,
        cms=np.zeros((SK.cms_depth, SK.cms_width), np.int64), hist=hist,
        tk_hi=np.zeros(0, np.uint32), tk_lo=np.zeros(0, np.uint32),
        tk_ida=np.zeros(0, np.uint32), tk_idb=np.zeros(0, np.uint32),
        tk_votes=np.zeros(0, np.int64),
    )
    rows = sketch_block_rows(blk, 1)
    q_services = {r[2]["service"] for r in rows if r[1] == SKETCH_METRIC_QUANTILE}
    assert q_services == {"1"}
    assert all(r[3] > 0 for r in rows if r[1] == SKETCH_METRIC_QUANTILE)


def test_held_sketch_blocks_are_bounded_drop_oldest():
    """Review pin: an undrained pop_closed_sketches must not leak a
    block per closed window — beyond max_held_sketches the oldest drop
    and are COUNTED."""
    from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
    from deepflow_tpu.datamodel.batch import FlowBatch
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    pipe = L4Pipeline(
        PipelineConfig(window=WindowConfig(capacity=1 << 12, sketch=SK),
                       batch_size=256)
    )
    pipe.max_held_sketches = 2
    gen = SyntheticFlowGen(num_tuples=100, seed=71)
    for i in range(7):  # one window closes per batch after warmup
        pipe.ingest(FlowBatch.from_records(gen.records(64, T0 + i)))
    pipe.drain()
    c = pipe.get_counters()
    assert c["sketch_blocks_held"] <= 2
    assert c["sketch_blocks_dropped"] >= 1
    held = pipe.pop_closed_sketches()
    assert len(held) <= 2
    # the survivors are the NEWEST windows
    assert held == sorted(held, key=lambda b: b.window)


def test_closing_rows_never_alias_into_older_open_slot():
    """Review pin (r12 second pass): a batch whose own t_min jumps
    ahead of a window still open from an earlier batch must NOT fold
    mod-R-aliasing rows into that older slot — the collision-free span
    anchors at the oldest LIVE ring slot, and out-of-span closing rows
    are counted-shed. Before the fix, window 0's block absorbed window
    4's rows (n_updates 5, polluted HLL/CMS/top-K) with shed == 0."""
    wm = _wm(delay=2)  # R = 4: windows 0 and 4 share ring slot 0
    out = list(wm.ingest(*_doc_batch(np.array([1, 2, 3], np.uint32), 0)))
    b = list(_doc_batch(np.array([10, 11, 12, 20, 21, 30, 31], np.uint32), 0))
    b[0] = np.array([1, 2, 3, 4, 4, 7, 7], np.uint32)
    out += wm.ingest(*b)
    out += wm.flush_all()
    by_win = {f.window_idx: f for f in out}
    # window 0 closed with ONLY its own 3 rows in the sketch block
    assert by_win[0].count == 3
    assert by_win[0].sketches is not None
    assert by_win[0].sketches.n_updates == 3
    assert abs(by_win[0].sketches.distinct() - 3) < 1.5
    # window 4's rows were mid-gap: exact rows flushed, sketch coverage
    # counted out (no silently-contaminated block anywhere)
    assert by_win[4].count == 2
    assert by_win[4].sketches is None
    assert wm.get_counters()["sketch_shed"] == 2
    # in-span windows keep clean per-window blocks
    for w in (1, 2, 3, 7):
        assert by_win[w].sketches.n_updates == by_win[w].count


def test_promql_rejects_unbalanced_parens():
    """Review pin: the topk() regex extension must not let a dropped or
    extra paren parse silently."""
    from deepflow_tpu.querier.promql import PromQLError, query_instant
    from deepflow_tpu.storage.store import ColumnarStore
    from deepflow_tpu.integration.dfstats import ensure_system_table

    store = ColumnarStore()
    ensure_system_table(store)
    for bad in ("topk(5, metric", "sum(metric))", "metric)"):
        with pytest.raises(PromQLError, match="parenthes"):
            query_instant(store, bad, T0, db="deepflow_system",
                          table="deepflow_system")
    # balanced forms still parse
    assert query_instant(store, "topk(5, metric)", T0, db="deepflow_system",
                         table="deepflow_system") == []
