"""Disaggregated sketch-memory pool (ISSUE 20) — pool-vs-slab
equivalence, promotion algebra, geometry validation, checkpoint v6
round-trips (single-chip AND sharded, mid-promotion), v5-into-pooled
loud re-init, spill accounting, and the shared-sort ring-fold pin.

Equivalence contract per lane (ops/{hll,cms,histogram,topk}.py):
  - HLL: compact slots keep the FULL m registers as int8 — promotion
    is a widening cast, so pooled HLL planes are BIT-EXACT vs slab.
  - log-hist: compact bins are exact coarsenings (bin // factor) and
    expansion re-centers mass — total mass is conserved EXACTLY.
  - CMS: compact rows are genuinely narrower (lossy); expansion tiles
    each compact count into all `cms_factor` congruent wide slots, so
    RAW pooled mass is slab × cms_factor while point-query estimates
    stay overestimate-only. Pins compare estimates, never raw counts.
  - top-K: compact buckets tile the same way; heavy-hitter recovery
    is the pinned surface.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepflow_tpu.aggregator.sketchplane import PoolConfig, SketchConfig
from deepflow_tpu.aggregator.window import WindowConfig, WindowManager
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ops.histogram import LogHistSpec

SK_SLAB = SketchConfig(
    num_groups=4, hll_precision=8, cms_depth=3, cms_width=512,
    hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.2),
    topk_rows=2, topk_cols=128, pending=10,
)
POOL = PoolConfig(compact_slots=3, wide_slots=1, cms_factor=8,
                  topk_factor=4, hist_factor=8, promote_fill=0.5)
SK_POOL = dataclasses.replace(SK_SLAB, pool=POOL)
T0 = 1_700_000_000


def _wm(sketch, capacity=1 << 11, delay=2, stats_ring=1):
    return WindowManager(
        WindowConfig(capacity=capacity, delay=delay, stats_ring=stats_ring,
                     sketch=sketch)
    )


def _doc_batch(keys: np.ndarray, t: int, byte_w=100.0):
    n = len(keys)
    keys = np.asarray(keys, np.uint32)
    tags = np.zeros((TAG_SCHEMA.num_fields, n), np.uint32)
    tags[TAG_SCHEMA.index("ip0_w3")] = keys
    tags[TAG_SCHEMA.index("server_port")] = 443
    tags[TAG_SCHEMA.index("protocol")] = 6
    tags[TAG_SCHEMA.index("l3_epc_id1")] = keys % 4
    meters = np.zeros((FLOW_METER.num_fields, n), np.float32)
    meters[FLOW_METER.index("byte_tx")] = byte_w
    meters[FLOW_METER.index("rtt_sum")] = 10.0
    meters[FLOW_METER.index("rtt_count")] = 1.0
    ts = np.full(n, t, np.uint32)
    hi = keys * np.uint32(2654435761) + np.uint32(1)
    lo = keys ^ np.uint32(0x9E3779B9)
    return (ts, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(tags),
            jnp.asarray(meters), jnp.ones(n, bool))


def _run(wm, batches):
    out = []
    for keys, t in batches:
        out.extend(wm.ingest(*_doc_batch(keys, t)))
    out.extend(wm.flush_all())
    return out


def _blocks(flushed):
    return {f.window_idx: f.sketches for f in flushed
            if f.sketches is not None}


# -- pool-vs-slab equivalence --------------------------------------------


def test_pool_vs_slab_closed_blocks_equal_accuracy():
    """The tentpole acceptance shape at test scale: the pooled plane
    closes the same windows with the same coverage, bit-exact HLL,
    mass-conserving histograms, in-envelope CMS estimates and the same
    recovered heavies — at a fraction of the slab's resident bytes
    (bench/sketchbench.py carries the measured ≥4× density)."""
    rng = np.random.default_rng(60)
    per_window = {}
    for t in (T0, T0 + 1, T0 + 2):
        k = np.concatenate([
            rng.integers(0, 250, 400).astype(np.uint32),
            np.repeat(np.arange(4, dtype=np.uint32), 60),  # real heavies
        ])
        rng.shuffle(k)
        per_window[t] = k
    batches = [(k, t) for t, k in per_window.items()]
    slab = _blocks(_run(_wm(SK_SLAB), batches))
    pool = _blocks(_run(_wm(SK_POOL), batches))
    assert set(slab) == set(pool) == set(per_window)
    for w, a in slab.items():
        b = pool[w]
        keys = per_window[w]
        assert a.n_updates == b.n_updates == len(keys)
        # HLL: full-m int8 compact registers → bit-exact
        np.testing.assert_array_equal(a.hll, b.hll)
        # log-hist: mass conserved exactly through coarsen/expand
        assert int(np.sum(a.hist)) == int(np.sum(b.hist))
        # CMS raw mass scales by cms_factor under tile expansion when
        # the window closed compact (estimates below are the real pin)
        assert int(np.sum(b.cms)) in (
            int(np.sum(a.cms)), int(np.sum(a.cms)) * POOL.cms_factor
        )
        # §17 accuracy envelope holds for the POOLED block
        true_distinct = len(np.unique(keys))
        assert abs(b.distinct() - true_distinct) / true_distinct < 0.15
        uniq, counts = np.unique(keys, return_counts=True)
        hi = uniq * np.uint32(2654435761) + np.uint32(1)
        lo = uniq ^ np.uint32(0x9E3779B9)
        est = b.estimate(hi, lo)
        true_bytes = counts * 100
        assert (est >= true_bytes).all()
        # compact CMS ε = e/width bound: overcount ≤ mass/(width/8)
        assert (est - true_bytes <= len(keys) * 100 / 8).all()
        heavy_true = set(uniq[np.argsort(-counts)][:3].tolist())
        heavy_rec = {t_["id_a"] for t_ in b.topk(5)}
        assert len(heavy_true & heavy_rec) >= 2
        assert abs(b.quantile(0.5) - 10.0) / 10.0 < 0.3


def test_promoted_window_matches_slab_build_over_full():
    """Merge-of-promoted == build-over-full, per lane: a window that
    starts compact, trips the saturation estimator mid-stream and
    finishes wide must close with the same answers as the slab plane
    fed the identical full stream — HLL bit-exact (promotion is a
    cast), hist mass exact, CMS/top-K within the envelope."""
    rng = np.random.default_rng(61)
    # two batches into ONE window: the first saturates the compact CMS
    # row (width 512/8 = 64 → well past promote_fill=0.5), the second
    # lands post-promotion in the wide slot
    first = rng.integers(0, 2000, 600).astype(np.uint32)
    second = np.concatenate([
        rng.integers(0, 2000, 200).astype(np.uint32),
        np.repeat(np.arange(6, dtype=np.uint32), 150),  # planted heavies
    ])
    rng.shuffle(second)
    batches = [(first, T0), (second, T0), (np.arange(8, dtype=np.uint32), T0 + 4)]
    wm_pool = _wm(SK_POOL)
    pool_out = _run(wm_pool, batches)
    assert wm_pool.get_counters()["sketch_promotions"] >= 1
    assert wm_pool.get_counters()["sketch_pool_spill"] == 0
    slab_out = _run(_wm(SK_SLAB), batches)
    a, b = _blocks(slab_out)[T0], _blocks(pool_out)[T0]
    stream = np.concatenate([first, second])
    assert a.n_updates == b.n_updates == len(stream)
    np.testing.assert_array_equal(a.hll, b.hll)  # bit-exact across promote
    assert int(np.sum(a.hist)) == int(np.sum(b.hist))
    true_distinct = len(np.unique(stream))
    assert abs(b.distinct() - true_distinct) / true_distinct < 0.15
    uniq, counts = np.unique(stream, return_counts=True)
    est = b.estimate(uniq * np.uint32(2654435761) + np.uint32(1),
                     uniq ^ np.uint32(0x9E3779B9))
    assert (est >= counts * 100).all()
    # the planted heavies dominate the promoted block's recovery
    heavy_rec = {t_["id_a"] for t_ in b.topk(6)}
    assert len(set(range(6)) & heavy_rec) >= 4


def test_lane_expansion_properties():
    """Direct per-lane pins of the promotion algebra the plane relies
    on: CMS tile-expansion preserves point-query estimates exactly;
    log-hist coarsen/expand round-trips mass and the quantile bin."""
    from deepflow_tpu.ops.cms import (
        cms_expand, cms_init, cms_query, cms_update,
    )
    from deepflow_tpu.ops.histogram import (
        loghist_coarsen_bin, loghist_expand,
    )

    rng = np.random.default_rng(62)
    hi = jnp.asarray(rng.integers(0, 1 << 32, 200, dtype=np.uint32))
    lo = jnp.asarray(rng.integers(0, 1 << 32, 200, dtype=np.uint32))
    w = jnp.ones(200, jnp.int32)
    valid = jnp.ones(200, bool)
    compact = cms_update(cms_init(3, 64), hi, lo, w, valid)
    wide = cms_expand(compact, 512)
    # every key hashed into the compact table reads the SAME estimate
    # out of the tiled wide table (congruent slots carry the count)
    np.testing.assert_array_equal(
        np.asarray(cms_query(compact, hi, lo)),
        np.asarray(cms_query(wide, hi, lo)),
    )
    # raw mass scales by exactly the tile factor
    assert int(jnp.sum(wide)) == int(jnp.sum(compact)) * (512 // 64)

    # hist: wide→compact bin mapping is exact integer division; expand
    # conserves mass and lands it inside the source coarse bin
    wide_bins = jnp.asarray(rng.integers(0, 64, 500, dtype=np.int32))
    coarse = loghist_coarsen_bin(wide_bins, 8)
    np.testing.assert_array_equal(np.asarray(coarse),
                                  np.asarray(wide_bins) // 8)
    compact_h = np.zeros((2, 8), np.int64)
    np.add.at(compact_h, (0, np.asarray(coarse)), 1)
    expanded = np.asarray(loghist_expand(jnp.asarray(compact_h), 64))
    assert expanded.shape == (2, 64)
    assert expanded.sum() == compact_h.sum()
    np.testing.assert_array_equal(
        expanded.reshape(2, 8, 8).sum(-1), compact_h
    )


# -- geometry validation --------------------------------------------------


@pytest.mark.parametrize("bad,match", [
    (dict(wide_slots=0), "wide_slots"),
    (dict(compact_slots=0), "compact_slots"),
    (dict(cms_factor=3), "power of two"),
    (dict(cms_factor=1024), "cannot promote the cms lane"),
    (dict(hist_factor=128), "cannot promote the hist lane"),
    (dict(topk_factor=256), "cannot promote the topk lane"),
    (dict(promote_fill=0.0), "promote_fill"),
    (dict(promote_fill=1.5), "promote_fill"),
])
def test_pool_geometry_rejected(bad, match):
    """SketchConfig must reject pool/ring geometries where promotion
    cannot fit the widest lane — at CONSTRUCTION, naming the lane, not
    as a shape error inside a jitted step."""
    with pytest.raises(ValueError, match=match):
        dataclasses.replace(SK_SLAB, pool=dataclasses.replace(POOL, **bad))


def test_pool_rejects_unpackable_hll():
    with pytest.raises(ValueError, match="divisible by 4"):
        SketchConfig(num_groups=2, hll_precision=1, cms_depth=2,
                     cms_width=64, hist=LogHistSpec(bins=16, vmin=1.0,
                                                    gamma=1.3),
                     topk_rows=0, topk_cols=8, pool=PoolConfig())


def test_pool_requires_cms_saturation_lane():
    with pytest.raises(ValueError, match="cms_depth"):
        dataclasses.replace(SK_SLAB, cms_depth=0, pool=POOL)


# -- spill accounting -----------------------------------------------------


def test_pool_exhaustion_spills_counted_not_silent():
    """More concurrently-open windows than pool slots: the overflow
    window loses sketch coverage COUNTED (sketch_pool_spill), the exact
    tier keeps every row, and no block is contaminated."""
    tiny = dataclasses.replace(
        SK_SLAB, pool=dataclasses.replace(POOL, compact_slots=1,
                                          wide_slots=1))
    wm = _wm(tiny, delay=2)  # R = 4 ring slots, but only 2 pool slots
    ks = np.arange(30, dtype=np.uint32)
    flushed = _run(wm, [(ks, T0), (ks, T0 + 1), (ks, T0 + 2), (ks, T0 + 3)])
    c = wm.get_counters()
    assert c["sketch_pool_spill"] > 0
    # exact rows flushed for EVERY window regardless of sketch spill
    assert sorted(f.window_idx for f in flushed) == [T0, T0 + 1, T0 + 2,
                                                     T0 + 3]
    assert all(f.count == 30 for f in flushed)
    # windows that did hold a slot close with clean blocks
    for f in flushed:
        if f.sketches is not None:
            assert f.sketches.n_updates == 30


def test_pool_occupancy_counter_moves():
    wm = _wm(SK_POOL)
    list(wm.ingest(*_doc_batch(np.arange(20, dtype=np.uint32), T0)))
    assert wm.get_counters()["sketch_pool_occ"] >= 1


# -- checkpoint v6 --------------------------------------------------------


def _ckpt_roundtrip_single(tmp_path, batches_pre, batches_post):
    """Run pool wm over pre-batches, checkpoint, continue original AND
    restored over post-batches; → (original flushed, restored flushed)."""
    from deepflow_tpu.aggregator.checkpoint import (
        load_window_state, save_window_state,
    )

    wm = _wm(SK_POOL)
    out_a = []
    for keys, t in batches_pre:
        out_a.extend(wm.ingest(*_doc_batch(keys, t)))
    ckpt = tmp_path / "pool.ckpt"
    out_a.extend(save_window_state(wm, ckpt))
    wm2 = load_window_state(ckpt, TAG_SCHEMA, FLOW_METER)
    out_b = list(out_a)
    for keys, t in batches_post:
        out_a.extend(wm.ingest(*_doc_batch(keys, t)))
        out_b.extend(wm2.ingest(*_doc_batch(keys, t)))
    out_a.extend(wm.flush_all())
    out_b.extend(wm2.flush_all())
    return out_a, out_b


def _assert_flushed_bit_exact(got, want):
    assert [f.window_idx for f in got] == [f.window_idx for f in want]
    for a, b in zip(got, want):
        assert a.count == b.count
        np.testing.assert_array_equal(a.key_hi, b.key_hi)
        np.testing.assert_array_equal(a.meters, b.meters)
        if a.sketches is None:
            assert b.sketches is None
            continue
        assert a.sketches.n_updates == b.sketches.n_updates
        for lane in ("hll", "cms", "hist", "tk_votes", "tk_hi", "tk_lo",
                     "tk_ida", "tk_idb"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a.sketches, lane)),
                np.asarray(getattr(b.sketches, lane)), err_msg=lane,
            )


def test_checkpoint_v6_mid_promotion_roundtrip_bit_exact(tmp_path):
    """Kill-mid-promotion: the checkpoint lands AFTER a window promoted
    compact→wide but BEFORE it closed. The restored manager must finish
    the window bit-exact vs the uninterrupted run — the wide arena,
    slot maps and saturation state all ride the v6 file."""
    rng = np.random.default_rng(63)
    pre = [(rng.integers(0, 2000, 600).astype(np.uint32), T0)]  # promotes
    post = [(rng.integers(0, 2000, 300).astype(np.uint32), T0),
            (np.arange(40, dtype=np.uint32), T0 + 1),
            (np.arange(40, dtype=np.uint32), T0 + 4)]
    wm_probe = _wm(SK_POOL)
    for keys, t in pre:
        list(wm_probe.ingest(*_doc_batch(keys, t)))
    wm_probe.settle()
    assert wm_probe.get_counters()["sketch_promotions"] >= 1, \
        "pre-batches must trip a promotion for this pin to bite"
    out_a, out_b = _ckpt_roundtrip_single(tmp_path, pre, post)
    _assert_flushed_bit_exact(out_b, out_a)


def test_checkpoint_v6_meta_records_pool(tmp_path):
    from deepflow_tpu.aggregator.checkpoint import (
        read_checkpoint_meta, save_window_state,
    )

    wm = _wm(SK_POOL)
    list(wm.ingest(*_doc_batch(np.arange(10, dtype=np.uint32), T0)))
    ckpt = tmp_path / "meta.ckpt"
    save_window_state(wm, ckpt)
    meta = read_checkpoint_meta(ckpt)
    assert meta["version"] >= 6
    assert meta["sketch"]["pool"] == POOL.meta()


def test_slab_file_into_pooled_manager_reinits_loudly(tmp_path, caplog):
    """The v5-compatibility contract: a file whose sketch meta carries
    no pool (v5 files and slab v6 files look identical here) restores
    into a pool-configured manager with the sketch tier re-initialized
    and a LOUD log — pooled arenas cannot be re-seated from slabs. The
    exact tier restores bit-exact regardless."""
    from deepflow_tpu.aggregator.checkpoint import (
        load_window_state, save_window_state,
    )

    wm = _wm(SK_SLAB)
    list(wm.ingest(*_doc_batch(np.arange(50, dtype=np.uint32), T0)))
    ckpt = tmp_path / "slab.ckpt"
    save_window_state(wm, ckpt)
    with caplog.at_level(logging.WARNING):
        wm2 = load_window_state(ckpt, TAG_SCHEMA, FLOW_METER,
                                sketch_config=SK_POOL)
    assert any("cannot be re-seated" in r.message for r in caplog.records)
    assert wm2.config.sketch.pool is not None
    # exact rows survived; the re-initialized pooled plane works
    flushed = _run(wm2, [(np.arange(50, dtype=np.uint32), T0 + 4)])
    assert sum(f.count for f in flushed) >= 50
    assert wm2.get_counters()["sketch_pool_spill"] == 0


def test_slab_checkpoint_still_roundtrips_bit_exact(tmp_path):
    """v5-shaped files (no pool) keep loading bit-exact — the pooled
    lanes synthesize zero-size, nothing shifts in the layout."""
    from deepflow_tpu.aggregator.checkpoint import (
        load_window_state, save_window_state,
    )

    rng = np.random.default_rng(64)
    wm = _wm(SK_SLAB)
    list(wm.ingest(*_doc_batch(rng.integers(0, 300, 200).astype(np.uint32),
                               T0)))
    ckpt = tmp_path / "slab2.ckpt"
    save_window_state(wm, ckpt)
    wm2 = load_window_state(ckpt, TAG_SCHEMA, FLOW_METER)
    out_a = _run(wm, [(np.arange(30, dtype=np.uint32), T0 + 4)])
    out_b = _run(wm2, [(np.arange(30, dtype=np.uint32), T0 + 4)])
    _assert_flushed_bit_exact(out_b, out_a)


# -- sharded twin ---------------------------------------------------------


def _sharded_cfg(pool):
    from deepflow_tpu.parallel.sharded import ShardedConfig

    return ShardedConfig(
        capacity_per_device=1 << 10, num_services=8, hll_precision=7,
        cms_depth=2, cms_width=256,
        hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
        topk_cols=64, sketch_pending=8, sketch_pool=pool,
    )


def _sharded_run(n_dev, pool, batches):
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedPipeline, ShardedWindowManager,
    )

    wm = ShardedWindowManager(
        ShardedPipeline(make_mesh(n_dev), _sharded_cfg(pool)))
    for fb in batches:
        wm.ingest(fb.tags, fb.meters, fb.valid)
    wm.drain()
    return wm, {b.window: b for b in wm.pop_closed_sketches()}


SH_POOL = PoolConfig(compact_slots=3, wide_slots=1, cms_factor=4,
                     topk_factor=2, hist_factor=4, promote_fill=0.5)


def test_sharded_pool_matches_slab_and_single_device():
    """Sharded twin equivalence: pooled blocks merge across the mesh to
    the same order-independent truth as slab blocks (HLL bit-exact,
    hist mass conserved) and a 2-device pooled run equals the 1-device
    pooled run bit-exact on merge-closed lanes."""
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    gen = SyntheticFlowGen(num_tuples=300, seed=54)
    batches = [gen.flow_batch(128, t) for t in (T0, T0 + 1, T0 + 4)]
    _, slab = _sharded_run(1, None, batches)
    wm_p1, pool1 = _sharded_run(1, SH_POOL, batches)
    wm_p2, pool2 = _sharded_run(2, SH_POOL, batches)
    assert set(slab) == set(pool1) == set(pool2)
    assert wm_p1.get_counters()["sketch_pool_spill"] == 0
    assert wm_p2.get_counters()["sketch_pool_spill"] == 0
    for w, a in slab.items():
        b1, b2 = pool1[w], pool2[w]
        assert a.n_updates == b1.n_updates == b2.n_updates
        np.testing.assert_array_equal(a.hll, b1.hll)  # pool vs slab
        assert int(np.sum(a.hist)) == int(np.sum(b1.hist))
        # mesh-merge determinism of the pooled plane itself
        np.testing.assert_array_equal(b1.hll, b2.hll)
        np.testing.assert_array_equal(b1.cms, b2.cms)
        np.testing.assert_array_equal(b1.hist, b2.hist)


def test_sharded_checkpoint_v6_mid_promotion_roundtrip(tmp_path):
    """Sharded kill-mid-promotion: checkpoint after a promoting batch,
    restore into a FRESH manager, continue both on identical traffic —
    closed blocks and counters must match bit-exact."""
    from deepflow_tpu.aggregator.checkpoint import (
        restore_sharded_state, save_sharded_state,
    )
    from deepflow_tpu.ingest.replay import SyntheticFlowGen
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedPipeline, ShardedWindowManager,
    )

    # few distinct tuples at high volume saturate the compact CMS row
    gen = SyntheticFlowGen(num_tuples=400, seed=57)
    pre = [gen.flow_batch(256, T0), gen.flow_batch(256, T0)]
    post = [gen.flow_batch(128, T0 + 1), gen.flow_batch(128, T0 + 4)]
    mk = lambda: ShardedWindowManager(
        ShardedPipeline(make_mesh(2), _sharded_cfg(SH_POOL)))
    wm = mk()
    for fb in pre:
        wm.ingest(fb.tags, fb.meters, fb.valid)
    wm.drain()
    assert wm.get_counters()["sketch_promotions"] >= 1, \
        "pre-batches must trip a promotion for this pin to bite"
    # blocks closed before the barrier already left the device state:
    # they belong to the pre-checkpoint output, not the comparison
    wm.pop_closed_sketches()
    ckpt = tmp_path / "sh_pool.ckpt"
    save_sharded_state(wm, ckpt)
    wm2 = mk()
    restore_sharded_state(wm2, ckpt)
    blocks = {}
    for m in (wm, wm2):
        for fb in post:
            m.ingest(fb.tags, fb.meters, fb.valid)
        m.drain()
        blocks[id(m)] = {b.window: b for b in m.pop_closed_sketches()}
    a, b = blocks[id(wm)], blocks[id(wm2)]
    assert set(a) == set(b) and len(a) >= 1
    for w in a:
        assert a[w].n_updates == b[w].n_updates
        for lane in ("hll", "cms", "hist", "tk_votes", "tk_hi", "tk_lo"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a[w], lane)),
                np.asarray(getattr(b[w], lane)), err_msg=lane,
            )
    ca, cb = wm.get_counters(), wm2.get_counters()
    # device-truth lanes ride the checkpoint and must agree exactly
    for k in ("sketch_promotions", "sketch_pool_spill"):
        assert ca[k] == cb[k], k
    # the original also closed the pre-barrier window (emitted before
    # the save), so its host-cumulative close count leads by exactly it
    assert ca["sketch_blocks_closed"] == cb["sketch_blocks_closed"] + 1


# -- shared-sort ring fold (ISSUE 20 satellite) ---------------------------


def test_tier_ring_fold_shared_sort_bit_exact():
    """The cascade's ring fold with the dispatch-owned shared order
    (shared_sort=True, rank-merge against the canonical tier prefix)
    must be BIT-EXACT vs the full two-array keyed sort across fills,
    including sentinel-invalid ring rows and the empty ring."""
    from deepflow_tpu.aggregator.cascade import _ring_fold_impl
    from deepflow_tpu.aggregator.stash import stash_fold, stash_init
    from tests.test_merge_fold import TINY_METER, TINY_TAGS, _rand_acc

    sum_cols = tuple(int(i) for i in np.nonzero(TINY_METER.sum_mask)[0])
    max_cols = tuple(int(i) for i in np.nonzero(TINY_METER.max_mask)[0])
    rng = np.random.default_rng(65)
    for fill in (0, 1, 37, 128):
        tier = stash_init(256, TINY_TAGS, TINY_METER)
        seed = _rand_acc(rng, 192, 150, n_windows=4, n_keys=40)
        tier, _ = stash_fold(tier, seed, TINY_METER)  # canonical prefix
        acc = _rand_acc(rng, 128, fill, n_windows=4, n_keys=40)
        lanes = jnp.zeros((2,), jnp.uint32)
        a_state, _, a_lanes = _ring_fold_impl(
            tier, acc, lanes, sum_cols, max_cols, shared_sort=False)
        b_state, _, b_lanes = _ring_fold_impl(
            tier, acc, lanes, sum_cols, max_cols, shared_sort=True)
        for f in ("slot", "key_hi", "key_lo", "tags", "meters", "valid",
                  "dropped_overflow"):
            np.testing.assert_array_equal(
                np.asarray(getattr(a_state, f)),
                np.asarray(getattr(b_state, f)),
                err_msg=f"fill={fill} lane={f}",
            )
        np.testing.assert_array_equal(np.asarray(a_lanes),
                                      np.asarray(b_lanes))


def test_stash_canonicalize_restores_sorted_prefix():
    """Restore-time repair for pre-v6 tier stashes: after punching a
    hole into the live prefix (the old non-compacting flush), one
    canonicalize pass re-establishes the sorted positional prefix and
    preserves every live row bit-for-bit."""
    from deepflow_tpu.aggregator.stash import (
        stash_canonicalize, stash_fold, stash_init,
    )
    from deepflow_tpu.ops.segment import SENTINEL_SLOT
    from tests.test_merge_fold import TINY_METER, TINY_TAGS, _rand_acc

    rng = np.random.default_rng(66)
    st = stash_init(128, TINY_TAGS, TINY_METER)
    st, _ = stash_fold(st, _rand_acc(rng, 128, 100, n_windows=4,
                                     n_keys=30), TINY_METER)
    live_before = {
        (int(h), int(l), int(s))
        for h, l, s, v in zip(np.asarray(st.key_hi), np.asarray(st.key_lo),
                              np.asarray(st.slot), np.asarray(st.valid))
        if v
    }
    # punch holes mid-prefix (what an old range flush left behind)
    slot = np.asarray(st.slot).copy()
    valid = np.asarray(st.valid).copy()
    holes = [i for i in range(len(valid)) if valid[i]][1:6]
    slot[holes] = np.uint32(SENTINEL_SLOT)
    valid[holes] = False
    broken = dataclasses.replace(st, slot=jnp.asarray(slot),
                                 valid=jnp.asarray(valid))
    fixed = stash_canonicalize(broken)
    v = np.asarray(fixed.valid)
    n_live = int(v.sum())
    assert v[:n_live].all() and not v[n_live:].any()  # positional prefix
    keys = np.stack([np.asarray(fixed.slot)[:n_live],
                     np.asarray(fixed.key_hi)[:n_live],
                     np.asarray(fixed.key_lo)[:n_live]], axis=1)
    assert all(tuple(keys[i]) <= tuple(keys[i + 1])
               for i in range(n_live - 1))  # (slot,key)-ascending
    live_after = {
        (int(np.asarray(fixed.key_hi)[i]), int(np.asarray(fixed.key_lo)[i]),
         int(np.asarray(fixed.slot)[i]))
        for i in range(n_live)
    }
    expect = {k for k in live_before
              if k not in {(int(np.asarray(st.key_hi)[i]),
                            int(np.asarray(st.key_lo)[i]),
                            int(np.asarray(st.slot)[i])) for i in holes}}
    assert live_after == expect
