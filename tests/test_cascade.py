"""ISSUE 9 multi-resolution rollup cascade: 1m/1h tiers as device-side
folds of closed 1s windows, replacing the double-ingest.

Pins: cascade 1m meters bit-exact vs the old `DoubleIngestPipeline`
oracle (incl. late rows spanning a minute boundary), tier sketch blocks
== merge of their children (the r12 associativity pins make order
immaterial), the hour tier as a fold of minutes, counted tier sheds,
the sharded per-device fold + host merge, counter dogfooding over SQL +
PromQL, the querier's tier routing, and the datasource listings."""

from __future__ import annotations

import dataclasses
from functools import reduce

import numpy as np
import pytest

from deepflow_tpu.aggregator.cascade import CascadeConfig
from deepflow_tpu.aggregator.pipeline import (
    DoubleIngestPipeline,
    DualGranularityPipeline,
    L4Pipeline,
    PipelineConfig,
)
from deepflow_tpu.aggregator.sketchplane import SketchConfig
from deepflow_tpu.aggregator.window import WindowConfig
from deepflow_tpu.datamodel.batch import FlowBatch
from deepflow_tpu.datamodel.code import DocumentFlag
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ingest.replay import SyntheticFlowGen
from deepflow_tpu.ops.histogram import LogHistSpec

T0 = 1_700_000_040  # 40s into a minute so the first 1m window closes fast

_SK = SketchConfig(
    num_groups=4, hll_precision=7, cms_depth=2, cms_width=256,
    hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
    topk_rows=2, topk_cols=64, pending=8,
)


def _stream(pipe, spans, *, n=100, tuples=50, seed=3):
    gen = SyntheticFlowGen(num_tuples=tuples, seed=seed)
    out = []
    for t in spans:
        out += pipe.ingest(FlowBatch.from_records(gen.records(n, t)))
    out += pipe.drain()
    return out


def _canonical_rows(docbatches):
    """Sorted (time, tags…, meters-bits…) tuples — the bit-exact
    comparison form (meters compare as raw f32 bits, not approximately)."""
    rows = []
    for db in docbatches:
        mbits = db.meters.astype(np.float32).view(np.uint32)
        for i in range(db.size):
            rows.append(
                (int(db.timestamp[i]),)
                + tuple(int(v) for v in db.tags[i])
                + tuple(int(v) for v in mbits[i])
            )
    return sorted(rows)


def _split(docs):
    sec = [db for fl, db in docs if fl == DocumentFlag.PER_SECOND_METRICS]
    minute = [db for fl, db in docs if fl == DocumentFlag.NONE]
    return sec, minute


# ---------------------------------------------------------------------------
# oracle pin: cascade == double-ingest


def test_cascade_minute_bit_exact_vs_double_ingest():
    """The cascade's 1m docs are BIT-EXACT vs the old double-ingest on
    an identical stream — including late rows that land in the previous
    minute after the stream has crossed the boundary (admitted by the
    1s gate: ≤ delay behind t_max)."""
    cfg = PipelineConfig(window=WindowConfig(capacity=1 << 14), batch_size=256)
    # T0+19/T0+21 straddle the minute boundary at T0+20; the second
    # T0+19 batch arrives AFTER the boundary crossed but within delay=2
    # of t_max, so both implementations admit it into minute 0
    spans = [T0, T0 + 19, T0 + 21, T0 + 19, T0 + 30, T0 + 90]
    new = _stream(DualGranularityPipeline(cfg), spans)
    old = _stream(DoubleIngestPipeline(cfg), spans)

    new_sec, new_min = _split(new)
    old_sec, old_min = _split(old)
    assert new_min and old_min
    # 1s stream untouched by the cascade
    assert _canonical_rows(new_sec) == _canonical_rows(old_sec)
    # 1m stream: same rows, same tags, same meter BITS
    assert _canonical_rows(new_min) == _canonical_rows(old_min)


def test_cascade_single_dispatch_per_batch():
    """The acceptance criterion's mechanism: dual-granularity ingest
    issues ONE fused device dispatch per batch — the shim owns exactly
    one pipeline, and its dispatch count equals the batch count (the
    double-ingest dispatched 2×)."""
    from deepflow_tpu.utils.spans import SPAN_INGEST_DISPATCH

    cfg = PipelineConfig(window=WindowConfig(capacity=1 << 12), batch_size=256)
    dual = DualGranularityPipeline(cfg)
    spans = [T0 + i for i in range(6)]
    _stream(dual, spans)
    assert dual.pipe.tracer.summary()[SPAN_INGEST_DISPATCH]["count"] == len(spans)

    old = DoubleIngestPipeline(cfg)
    _stream(old, spans)
    n_old = (
        old.second.tracer.summary()[SPAN_INGEST_DISPATCH]["count"]
        + old.minute.tracer.summary()[SPAN_INGEST_DISPATCH]["count"]
    )
    assert n_old == 2 * len(spans)


def test_minute_rows_merge_across_seconds():
    """One flow key hit in many seconds → ONE 1m row with summed
    meters (doc fingerprints carry no timestamp, so the tier fold's
    (parent, key) re-key merges the per-second rows)."""
    pipe = DualGranularityPipeline(
        PipelineConfig(window=WindowConfig(capacity=1 << 12), batch_size=256)
    )
    docs = _stream(pipe, [T0, T0 + 1, T0 + 2, T0 + 5], n=10, tuples=1, seed=5)
    sec, minute = _split(docs)
    n_min = sum(db.size for db in minute)
    n_sec = sum(db.size for db in sec)
    assert 0 < n_min < n_sec
    pkt = FLOW_METER.index("packet_tx")
    assert sum(db.meters[:, pkt].sum() for db in minute) == sum(
        db.meters[:, pkt].sum() for db in sec
    )


# ---------------------------------------------------------------------------
# sketch tier pin: merge-of-60 == the cascade's minute block


def _assert_blocks_equal(a, b):
    assert a.window == b.window and a.n_updates == b.n_updates
    for lane in ("hll", "cms", "hist", "tk_votes", "tk_hi", "tk_lo",
                 "tk_ida", "tk_idb"):
        np.testing.assert_array_equal(
            getattr(a, lane), getattr(b, lane), err_msg=(a.window, lane)
        )


def test_minute_sketch_block_equals_merge_of_children():
    """The cascade's 1m sketch block is exactly the r12-algebra merge of
    its closed 1s blocks (window order — but the associativity/
    commutativity pins in tests/test_sketches.py make any order equal
    for hll/cms/hist; candidate arrays concatenate in fold order)."""
    cfg = PipelineConfig(
        window=WindowConfig(
            capacity=1 << 12, sketch=_SK,
            cascade=CascadeConfig(intervals=(60,), capacity=1 << 12),
        ),
        batch_size=256,
    )
    pipe = L4Pipeline(cfg)
    gen = SyntheticFlowGen(num_tuples=80, seed=11)
    for t in (T0, T0 + 3, T0 + 8, T0 + 14, T0 + 19, T0 + 21, T0 + 90):
        pipe.ingest(FlowBatch.from_records(gen.records(128, t)))
    pipe.drain()
    pipe.pop_tier_docbatches()  # routes tier blocks into the held list
    children = pipe.pop_closed_sketches()
    tier_blocks = pipe.closed_tier_sketches
    assert children and tier_blocks

    by_parent: dict[int, list] = {}
    for blk in children:
        by_parent.setdefault(blk.window // 60, []).append(blk)
    got = {b.window: b for b in tier_blocks}
    assert set(got) == set(by_parent)
    for parent, blks in by_parent.items():
        blks = sorted(blks, key=lambda b: b.window)
        want = reduce(
            lambda a, b: a.merge(dataclasses.replace(b, window=parent)),
            blks[1:],
            dataclasses.replace(blks[0], window=parent),
        )
        _assert_blocks_equal(got[parent], want)
        # ...and the minute answers come straight off the merged block
        assert got[parent].distinct() == want.distinct()


# ---------------------------------------------------------------------------
# hour tier + shed accounting


def test_hour_tier_folds_minutes():
    cfg = PipelineConfig(
        window=WindowConfig(
            capacity=1 << 14,
            cascade=CascadeConfig(intervals=(60, 3600), capacity=1 << 14),
        ),
        batch_size=256,
    )
    pipe = L4Pipeline(cfg)
    gen = SyntheticFlowGen(num_tuples=40, seed=13)
    for t in (T0, T0 + 30, T0 + 90, T0 + 3700, T0 + 7300):
        pipe.ingest(FlowBatch.from_records(gen.records(100, t)))
    sec_rows = sum(db.size for db in pipe.drain())
    tiers = pipe.pop_tier_docbatches()
    minutes = [db for iv, db in tiers if iv == 60]
    hours = [db for iv, db in tiers if iv == 3600]
    assert sec_rows and minutes and hours
    assert all((db.timestamp % 60 == 0).all() for db in minutes)
    assert all((db.timestamp % 3600 == 0).all() for db in hours)
    pkt = FLOW_METER.index("packet_tx")
    m_min = sum(db.meters[:, pkt].sum() for db in minutes)
    m_hr = sum(db.meters[:, pkt].sum() for db in hours)
    assert m_min == m_hr > 0
    c = pipe.get_counters()
    # tier folds consumed the 1s rows AND the 1m rows (counted once per
    # fold each) — strictly more fold work than 1s rows alone
    assert c["cascade_rows"] > sec_rows
    assert c["cascade_shed"] == 0


def test_tier_stash_overflow_is_counted_never_silent():
    cfg = PipelineConfig(
        window=WindowConfig(
            capacity=1 << 12,
            cascade=CascadeConfig(intervals=(60,), capacity=64),
        ),
        batch_size=512,
    )
    pipe = L4Pipeline(cfg)
    gen = SyntheticFlowGen(num_tuples=400, seed=17)
    for t in (T0, T0 + 10, T0 + 90):
        pipe.ingest(FlowBatch.from_records(gen.records(400, t)))
    sec_rows = sum(db.size for db in pipe.drain())
    tier_rows = sum(db.size for _iv, db in pipe.pop_tier_docbatches())
    c = pipe.get_counters()
    assert sec_rows > 0  # the 1s stream is unaffected by tier overflow
    assert c["cascade_shed"] > 0  # a 64-row minute stash must shed
    assert tier_rows <= 64 * 2  # bounded by tier capacity per minute


# ---------------------------------------------------------------------------
# sharded: per-device tier fold, host-merge at drain


def test_sharded_cascade_minute_matches_second_rollup():
    import jax  # noqa: F401 — mesh needs a backend

    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=6,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
        cascade=(60,), cascade_capacity=1 << 10,
    )
    wm = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    gen = SyntheticFlowGen(num_tuples=200, seed=5)
    docs = []
    for t in (T0, T0 + 1, T0 + 4, T0 + 30, T0 + 90):
        fb = gen.flow_batch(128, t)
        docs += wm.ingest(fb.tags, fb.meters, fb.valid)
    docs += wm.drain()
    tiers = wm.pop_tier_docbatches()
    assert tiers and all(iv == 60 for iv, _ in tiers)
    assert all((db.timestamp % 60 == 0).all() for _iv, db in tiers)

    # host oracle: roll the 1s docs up by (minute, full tag row) — the
    # sharded tier keeps per-device rows, so compare SUMMED meters per
    # (minute, tag row), which is device-layout independent. Only the
    # SUM-semantics meter columns add linearly across seconds (MAX
    # columns take the max — covered by the single-chip bit-exact pin).
    sum_cols = np.nonzero(FLOW_METER.sum_mask)[0]

    def grouped(dbs, bucket):
        out: dict = {}
        for db in dbs:
            for i in range(db.size):
                key = (int(db.timestamp[i]) // bucket * bucket,
                       tuple(int(v) for v in db.tags[i]))
                out[key] = out.get(key, 0.0) + float(db.meters[i][sum_cols].sum())
        return out

    want = grouped(docs, 60)
    got = grouped([db for _iv, db in tiers], 60)
    assert got == want
    c = wm.get_counters()
    assert c["cascade_rows"] > 0 and c["cascade_shed"] == 0
    assert c["cascade_tier_windows"] == len(tiers)


# ---------------------------------------------------------------------------
# dogfooding: cascade lanes over SQL + PromQL (deepflow_system)


def test_cascade_counters_roundtrip_sql_and_promql():
    from deepflow_tpu.integration.dfstats import system_metric_name, system_sink
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.querier.promql import query_instant
    from deepflow_tpu.storage.store import ColumnarStore
    from deepflow_tpu.utils.stats import StatsCollector

    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(
            capacity=1 << 12,
            cascade=CascadeConfig(intervals=(60,), capacity=1 << 12),
        ),
        batch_size=256,
    ))
    gen = SyntheticFlowGen(num_tuples=50, seed=3)
    for t in (T0, T0 + 30, T0 + 90):
        pipe.ingest(FlowBatch.from_records(gen.records(100, t)))
    expected = pipe.get_counters()
    assert expected["cascade_rows"] > 0

    store = ColumnarStore()
    col = StatsCollector(interval_s=999)
    col.register("tpu_pipeline", pipe, kind="L4Pipeline", interval="1s")
    col.add_sink(system_sink(store))
    col.tick(now=float(T0 + 100))

    eng = QueryEngine(store)
    for field in ("cascade_rows", "cascade_shed", "cascade_tier_windows"):
        metric = system_metric_name("tpu_pipeline", field)
        res = eng.execute(
            "SELECT value FROM deepflow_system.deepflow_system "
            f"WHERE metric = '{metric}'"
        )
        assert res.rows == 1, field
        assert float(res.values["value"][0]) == float(expected[field]), field
    out = query_instant(
        store, system_metric_name("tpu_pipeline", "cascade_rows"),
        T0 + 100, db="deepflow_system", table="deepflow_system",
    )
    assert len(out) == 1
    assert out[0]["value"] == float(expected["cascade_rows"])


# ---------------------------------------------------------------------------
# querier: tier routing


def test_querier_routes_range_queries_to_coarsest_satisfying_tier():
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.storage.store import ColumnarStore, ColumnSpec, TableSchema

    store = ColumnarStore()
    span = 3 * 3600  # a 3h range at 1s vs tier resolution
    for name, iv in (("network_1s", 1), ("network_1m", 60), ("network_1h", 3600)):
        store.create_table("flow_metrics", TableSchema(
            name,
            (ColumnSpec("time", "u4"), ColumnSpec("protocol", "u4"),
             ColumnSpec("byte_tx", "f4")),
            partition_s=3600,
        ))
        n = span // iv
        store.insert("flow_metrics", name, {
            "time": (np.arange(n) * iv).astype(np.uint32),
            "protocol": np.full(n, 6, np.uint32),
            "byte_tx": np.full(n, float(iv), np.float32),
        })
    eng = QueryEngine(store)
    # coarse steps read the matching tier: row count ≤ span/step per
    # series, never a 1s replay (the acceptance criterion)
    r = eng.execute(
        "select interval(time, 3600) as t, Sum(byte_tx) as b "
        "from network group by t order by t"
    )
    assert r.rows == 3  # 3 tier rows — not 10800 replayed seconds
    r = eng.execute(
        "select interval(time, 60) as t, Sum(byte_tx) as b "
        "from network group by t"
    )
    assert r.rows == span // 60
    # detail queries stay on the finest tier
    r = eng.execute("select Count() as c from network")
    assert int(r.values["c"][0]) == span
    # explicit granularity is never rerouted
    r = eng.execute(
        "select interval(time, 3600) as t, Count() as c from network.1s group by t"
    )
    assert int(np.asarray(r.values["c"]).sum()) == span
    # a step no tier divides falls back to the finest (correctness over
    # coarseness: 90s buckets over 1m rows would split tier rows)
    r = eng.execute(
        "select interval(time, 90) as t, Count() as c from network group by t"
    )
    assert int(np.asarray(r.values["c"]).sum()) == span


# ---------------------------------------------------------------------------
# datasource listings


def test_datasource_listing_reflects_cascade_tiers():
    from deepflow_tpu.server.datasource import (
        list_cascade_tiers,
        register_cascade_tiers,
    )

    register_cascade_tiers("flow", (60, 3600))
    rows = list_cascade_tiers()
    names = {r["name"] for r in rows}
    assert {"network_1m", "network_1h", "network_map_1m"} <= names
    assert all(r["served_by"] == "cascade" for r in rows)
    # constructing a cascade-enabled pipeline self-registers
    L4Pipeline(PipelineConfig(
        window=WindowConfig(
            capacity=1 << 10,
            cascade=CascadeConfig(intervals=(60,), capacity=1 << 10),
        ),
        batch_size=128,
    ))
    assert {"network_1m", "network_map_1m"} <= {
        r["name"] for r in list_cascade_tiers()
    }


def test_cascade_config_validation():
    with pytest.raises(ValueError, match="multiple"):
        CascadeConfig(intervals=(60, 90)).validate_base(1)
    with pytest.raises(ValueError, match="ascending"):
        CascadeConfig(intervals=(3600, 60))
    # 1m over a 60s base pipeline is NOT a proper multiple (equal)
    with pytest.raises(ValueError, match="multiple"):
        WindowConfig(interval=60, cascade=CascadeConfig(intervals=(60,)))


# ---------------------------------------------------------------------------
# review regression (ISSUE 9): a tier window whose children were ALL
# sketch-only must emit at the drain that closes it even when that
# drain transfers nothing (no exact rows anywhere, no new blocks) —
# the early-return fast path must not leak the merged parent block
# (the watermark has already advanced past it, so no later drain would
# ever release it).


def _empty_block(window: int):
    from deepflow_tpu.aggregator.sketchplane import WindowSketchBlock

    g, m = _SK.num_groups, _SK.hll_m
    return WindowSketchBlock(
        window=window, config=_SK, n_updates=7,
        hll=np.zeros((g, m), np.int32),
        cms=np.zeros((_SK.cms_depth, _SK.cms_width), np.int64),
        hist=np.zeros((g, _SK.hist.bins), np.int64),
        tk_hi=np.zeros((0,), np.uint32), tk_lo=np.zeros((0,), np.uint32),
        tk_ida=np.zeros((0,), np.uint32), tk_idb=np.zeros((0,), np.uint32),
        tk_votes=np.zeros((0,), np.int64),
    )


def test_sketch_only_tier_window_survives_empty_drain():
    from deepflow_tpu.aggregator.stash import stash_flush_range
    from deepflow_tpu.aggregator.window import WindowManager

    wm = WindowManager(WindowConfig(
        capacity=64, sketch=_SK,
        cascade=CascadeConfig(intervals=(60,), capacity=64),
    ))
    # a sketch-only minute: children merged into the pending parent,
    # zero exact rows anywhere
    wm.cascade.feed_block(0, 59, _empty_block(59))
    wm.state, packed, total = stash_flush_range(
        wm.state, np.uint32(0), np.uint32(100)
    )
    entry = wm._make_flush_entry(packed, total, 0, 100)
    assert entry.tiers, "hi=100 crosses the minute boundary — tier must flush"
    flushed = wm._drain_flush(entry)
    assert flushed == []  # no exact 1s windows — nothing to emit there
    tiers = wm.pop_tier_windows()
    assert len(tiers) == 1 and tiers[0].count == 0
    assert tiers[0].window_idx == 0 and tiers[0].sketches is not None
    assert tiers[0].sketches.n_updates == 7
    assert not wm.cascade.pending_blocks[0], "pending parent leaked"


def test_sketch_only_tier_window_survives_empty_drain_sharded():
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=64, num_services=4, hll_precision=7,
        cms_depth=2, cms_width=256,
        hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
        topk_cols=64, sketch_pending=8,
        cascade=(60,), cascade_capacity=64,
    )
    wm = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    blk = _empty_block(59)
    blk = dataclasses.replace(blk, config=wm._sk_cfg)
    wm._feed_tier_block(0, 59, blk)
    flushed = wm._drain_range(0, 100)
    assert flushed == []
    tiers = wm.pop_tier_docbatches()
    assert tiers == []  # no exact tier rows → no DocBatch...
    assert len(wm.closed_tier_sketches) == 1  # ...but the block released
    assert wm.closed_tier_sketches[0].n_updates == 7
    assert not wm._tier_pending_blocks[0], "pending parent leaked"


def test_shim_never_routes_coarse_tiers_into_minute_tables():
    """Review regression: route_table_ids only distinguishes PER_SECOND
    vs NONE, so a (60, 3600) shim must emit ONLY the 1m tier as NONE —
    hourly batches in the *_1m tables would double-count the hour."""
    cfg = PipelineConfig(
        window=WindowConfig(
            capacity=1 << 14,
            cascade=CascadeConfig(intervals=(60, 3600), capacity=1 << 14),
        ),
        batch_size=256,
    )
    pipe = DualGranularityPipeline(cfg)
    docs = _stream(pipe, [T0, T0 + 90, T0 + 3700, T0 + 7300], n=50, tuples=20)
    _sec, minute = _split(docs)
    assert minute and all((db.timestamp % 60 == 0).all() for db in minute)
    # the hourly batches surfaced out-of-band, not as NONE docs
    assert pipe.coarse_tiers and all(iv == 3600 for iv, _ in pipe.coarse_tiers)
    hr_rows = sum(db.size for _iv, db in pipe.coarse_tiers)
    min_rows = sum(db.size for db in minute)
    assert 0 < hr_rows < min_rows

    # conflicting explicit cascade params fail loudly, and a cascade
    # without a 1m tier cannot back the shim's minute contract
    with pytest.raises(ValueError, match="conflicting"):
        DualGranularityPipeline(
            cfg, cascade=CascadeConfig(intervals=(60,), capacity=1 << 12)
        )
    with pytest.raises(ValueError, match="1m cascade tier"):
        DualGranularityPipeline(PipelineConfig(window=WindowConfig(
            capacity=1 << 12,
            cascade=CascadeConfig(intervals=(3600,), capacity=1 << 12),
        )))


def test_tier_router_refuses_steps_finer_than_every_tier():
    """Review regression: a step finer than the finest available tier
    must NOT silently coarsen (60s rows in 30s buckets = a wrong
    series) — the router returns None and the query fails loudly."""
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.querier.sqlparse import SQLError
    from deepflow_tpu.querier.translation import select_datasource_tier
    from deepflow_tpu.storage.store import ColumnarStore, ColumnSpec, TableSchema

    assert select_datasource_tier({"network_1m": 60}, 30) is None
    assert select_datasource_tier({"network_1m": 60}, 60) == "network_1m"
    assert select_datasource_tier({"network_1m": 60}, None) == "network_1m"

    store = ColumnarStore()
    store.create_table("flow_metrics", TableSchema(
        "network_1m",
        (ColumnSpec("time", "u4"), ColumnSpec("byte_tx", "f4")),
        partition_s=3600,
    ))
    store.insert("flow_metrics", "network_1m", {
        "time": np.arange(4, dtype=np.uint32) * 60,
        "byte_tx": np.ones(4, np.float32),
    })
    eng = QueryEngine(store)
    r = eng.execute(
        "select interval(time, 60) as t, Sum(byte_tx) as b from network group by t"
    )
    assert r.rows == 4
    with pytest.raises(SQLError, match="no such table"):
        eng.execute(
            "select interval(time, 30) as t, Sum(byte_tx) as b "
            "from network group by t"
        )
