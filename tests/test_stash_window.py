import jax.numpy as jnp
import numpy as np

from deepflow_tpu.datamodel.schema import MergeOp, MeterField, MeterSchema, TagField, TagSchema
from deepflow_tpu.aggregator.stash import stash_flush, stash_init, stash_merge
from deepflow_tpu.aggregator.window import WindowConfig, WindowManager

TINY_METER = MeterSchema(
    "tiny",
    (
        MeterField("a", MergeOp.SUM),
        MeterField("b", MergeOp.SUM),
        MeterField("mx", MergeOp.MAX),
    ),
)
TINY_TAGS = TagSchema((TagField("k1"), TagField("k2")))


def _mkbatch(rows):
    """rows: list of (slot, hi, lo, (k1,k2), (a,b,mx))"""
    n = len(rows)
    slot = jnp.asarray(np.array([r[0] for r in rows], dtype=np.uint32))
    hi = jnp.asarray(np.array([r[1] for r in rows], dtype=np.uint32))
    lo = jnp.asarray(np.array([r[2] for r in rows], dtype=np.uint32))
    tags = jnp.asarray(np.array([r[3] for r in rows], dtype=np.uint32).T)
    meters = jnp.asarray(np.array([r[4] for r in rows], dtype=np.float32).T)
    valid = jnp.ones((n,), dtype=bool)
    return slot, hi, lo, tags, meters, valid


def test_stash_merge_accumulates_across_batches():
    st = stash_init(8, TINY_TAGS, TINY_METER)
    b1 = _mkbatch([(1, 10, 0, (7, 8), (1, 2, 5)), (1, 11, 0, (9, 9), (10, 0, 1))])
    st = stash_merge(st, *b1, TINY_METER)
    b2 = _mkbatch([(1, 10, 0, (7, 8), (4, 4, 2))])
    st = stash_merge(st, *b2, TINY_METER)

    st, out = stash_flush(st, 1)
    assert int(out["count"]) == 2
    mask = np.asarray(out["mask"])
    meters = np.asarray(out["meters"]).T[mask]
    his = np.asarray(out["key_hi"])[mask]
    row = {int(h): m for h, m in zip(his, meters)}
    np.testing.assert_array_equal(row[10], [5, 6, 5])  # sums + max
    np.testing.assert_array_equal(row[11], [10, 0, 1])
    # flushed rows are gone
    st, out2 = stash_flush(st, 1)
    assert int(out2["count"]) == 0


def test_stash_overflow_drops_newest_window():
    st = stash_init(4, TINY_TAGS, TINY_METER)
    # window 1: two keys; window 2: four keys → 6 segments > capacity 4
    rows = [(1, i, 0, (i, 0), (1, 0, 0)) for i in (1, 2)]
    rows += [(2, i, 0, (i, 0), (1, 0, 0)) for i in (1, 2, 3, 4)]
    st = stash_merge(st, *_mkbatch(rows), TINY_METER)
    assert int(st.dropped_overflow) == 2
    # older window fully retained
    st, out = stash_flush(st, 1)
    assert int(out["count"]) == 2


def test_window_manager_flushes_after_delay():
    wm = WindowManager(WindowConfig(interval=1, delay=2, capacity=16), TINY_TAGS, TINY_METER)

    def batch(ts_list, key_list):
        n = len(ts_list)
        ts = np.array(ts_list, dtype=np.uint32)
        hi = np.array(key_list, dtype=np.uint32)
        lo = np.zeros(n, dtype=np.uint32)
        tags = np.stack([hi, hi], axis=0).astype(np.uint32)
        meters = np.ones((3, n), dtype=np.float32)
        return (
            jnp.asarray(ts),
            jnp.asarray(hi),
            jnp.asarray(lo),
            jnp.asarray(tags),
            jnp.asarray(meters),
            jnp.ones(n, dtype=bool),
        )

    # t=100,101 → nothing closes yet (delay 2)
    assert wm.ingest(*batch([100, 100, 101], [1, 1, 2])) == []
    # t=103 → window 100 closes (103-2=101 > 100)
    flushed = wm.ingest(*batch([103], [3]))
    assert [f.window_idx for f in flushed] == [100]
    f = flushed[0]
    assert f.count == 1  # key 1 merged twice in window 100
    assert int(f.key_hi[0]) == 1
    np.testing.assert_array_equal(f.meters[0], [2, 2, 1])

    # late arrival for window 100 is dropped
    assert wm.ingest(*batch([100], [9])) == []
    assert wm.drop_before_window == 1

    # drain
    rest = wm.flush_all()
    assert [f.window_idx for f in rest] == [101, 103]
    assert wm.counters["occupancy"] == 0


def test_window_manager_growing_batch_keeps_accumulated_rows():
    """Regression: a batch larger than the accumulator ring re-initializes
    it; pending rows must be folded into the stash first, not dropped."""
    wm = WindowManager(
        WindowConfig(interval=1, delay=2, capacity=64, accum_batches=2),
        TINY_TAGS,
        TINY_METER,
    )

    def batch(n, ts, key0):
        return (
            jnp.full((n,), ts, dtype=jnp.uint32),
            jnp.asarray(np.arange(key0, key0 + n, dtype=np.uint32)),
            jnp.zeros(n, dtype=jnp.uint32),
            jnp.zeros((2, n), dtype=jnp.uint32),
            jnp.ones((3, n), dtype=jnp.float32),
            jnp.ones(n, dtype=bool),
        )

    wm.ingest(*batch(2, 50, 0))  # ring sized 2×2=4, fill=2
    wm.ingest(*batch(8, 50, 100))  # bigger than ring → re-init path
    flushed = wm.ingest(*batch(1, 60, 999))  # close window 50
    assert sum(f.count for f in flushed) == 10  # 2 + 8, nothing lost


def test_window_manager_multi_window_batch():
    wm = WindowManager(WindowConfig(interval=1, delay=1, capacity=32), TINY_TAGS, TINY_METER)
    ts = [10, 11, 12, 13, 14]
    n = len(ts)
    b = (
        jnp.asarray(np.array(ts, dtype=np.uint32)),
        jnp.asarray(np.arange(n, dtype=np.uint32)),
        jnp.zeros(n, dtype=jnp.uint32),
        jnp.zeros((2, n), dtype=jnp.uint32),
        jnp.ones((3, n), dtype=jnp.float32),
        jnp.ones(n, dtype=bool),
    )
    flushed = wm.ingest(*b)
    # t_max=14, delay=1 → windows 10..12 close
    assert [f.window_idx for f in flushed] == [10, 11, 12]
    assert all(f.count == 1 for f in flushed)
