"""Kafka exporter wire-protocol tests: CRC32C known-answer vectors, an
independent decode of the produced RecordBatch v2, and the exporter →
fake-broker round trip incl. acks=1 (reference:
ingester/exporters/kafka_exporter/)."""

from __future__ import annotations

import json
import socket
import struct
import threading

import numpy as np

from deepflow_tpu.server.kafka_exporter import (
    KafkaExporter,
    crc32c,
    encode_produce_request,
    encode_record_batch,
)


def test_crc32c_known_answers():
    # RFC 3720 B.4 / standard Castagnoli vectors
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43
    assert crc32c(bytes(range(32))) == 0x46DD794E


def _unzig(v):
    return (v >> 1) ^ -(v & 1)


def _read_varint(buf, off):
    out = 0
    shift = 0
    while True:
        b = buf[off]
        off += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzig(out), off
        shift += 7


def _decode_batch(batch: bytes):
    """Independent RecordBatch v2 decoder (not the encoder inverted —
    field offsets hand-derived from the Kafka protocol spec)."""
    base_offset, body_len = struct.unpack(">qi", batch[:12])
    body = batch[12:12 + body_len]
    leader_epoch, magic = struct.unpack(">ib", body[:5])
    crc, = struct.unpack(">I", body[5:9])
    assert magic == 2
    assert crc == crc32c(body[9:])  # checksum spans attributes..records
    attrs, last_off = struct.unpack(">hi", body[9:15])
    first_ts, max_ts, pid, pepoch, bseq, count = struct.unpack(
        ">qqqhii", body[15:49]
    )
    out = []
    off = 49
    for _ in range(count):
        ln, off = _read_varint(body, off)
        end = off + ln
        off += 1  # attributes
        _, off = _read_varint(body, off)  # ts delta
        _, off = _read_varint(body, off)  # offset delta
        klen, off = _read_varint(body, off)
        key = bytes(body[off:off + klen]) if klen >= 0 else None
        off += max(klen, 0)
        vlen, off = _read_varint(body, off)
        value = bytes(body[off:off + vlen])
        off = end
        out.append((key, value))
    return {"first_ts": first_ts, "count": count, "records": out,
            "base_offset": base_offset}


def test_record_batch_decodes_independently():
    recs = [(b"k1", b"v1"), (None, b"{}"), (b"k3", b"x" * 200)]
    batch = encode_record_batch(recs, 1_700_000_000_000)
    d = _decode_batch(batch)
    assert d["count"] == 3 and d["first_ts"] == 1_700_000_000_000
    assert d["records"] == recs


def _parse_produce(frame: bytes):
    size, = struct.unpack(">i", frame[:4])
    body = frame[4:4 + size]
    api, ver, corr = struct.unpack(">hhi", body[:8])
    off = 8
    cl, = struct.unpack(">h", body[off:off + 2]); off += 2
    client = body[off:off + cl].decode(); off += cl
    tl, = struct.unpack(">h", body[off:off + 2]); off += 2  # txn id (-1)
    assert tl == -1
    acks, timeout, ntopics = struct.unpack(">hii", body[off:off + 10])
    off += 10
    tl, = struct.unpack(">h", body[off:off + 2]); off += 2
    topic = body[off:off + tl].decode(); off += tl
    nparts, part, blen = struct.unpack(">iii", body[off:off + 12])
    off += 12
    batch = body[off:off + blen]
    return {"api": api, "ver": ver, "corr": corr, "client": client,
            "acks": acks, "topic": topic, "partition": part,
            "batch": batch}


def test_produce_request_layout():
    frame = encode_produce_request(
        "deepflow.network", [(b"network", b"{}")], correlation_id=7,
        acks=1, timestamp_ms=123,
    )
    p = _parse_produce(frame)
    assert (p["api"], p["ver"], p["corr"]) == (0, 3, 7)
    assert p["topic"] == "deepflow.network" and p["partition"] == 0
    assert p["acks"] == 1
    assert _decode_batch(p["batch"])["records"] == [(b"network", b"{}")]


class _FakeBroker:
    def __init__(self, acks: int):
        self.acks = acks
        self.produced = []
        self.srv = socket.create_server(("127.0.0.1", 0))
        self.port = self.srv.getsockname()[1]
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        conn, _ = self.srv.accept()
        try:
            while True:
                hdr = self._read(conn, 4)
                if hdr is None:
                    return
                size, = struct.unpack(">i", hdr)
                body = self._read(conn, size)
                if body is None:
                    return
                p = _parse_produce(hdr + body)
                self.produced.append(p)
                if self.acks:
                    # minimal Produce v3 response: corr + empty topics +
                    # throttle (enough framing for the client to drain)
                    resp = struct.pack(">ii", p["corr"], 0) + struct.pack(">i", 0)
                    conn.sendall(struct.pack(">i", len(resp)) + resp)
        except OSError:
            pass

    @staticmethod
    def _read(conn, n):
        out = b""
        while len(out) < n:
            c = conn.recv(n - len(out))
            if not c:
                return None
            out += c
        return out


def test_exporter_round_trip_acks0_and_acks1():
    for acks in (0, 1):
        broker = _FakeBroker(acks)
        exp = KafkaExporter("127.0.0.1", broker.port, acks=acks,
                            data_sources=("network",))
        cols = {
            "time": np.array([1_700_000_000, 1_700_000_000], np.uint32),
            "byte_tx": np.array([5.0, 7.0], np.float32),
            "pod": np.array(["p1", "p2"]),
        }
        exp.export("network", cols)
        assert exp.get_counters()["batches"] == 1, exp.get_counters()
        deadline = 50
        while not broker.produced and deadline:
            import time

            time.sleep(0.02)
            deadline -= 1
        p = broker.produced[0]
        assert p["topic"] == "deepflow.network"
        recs = _decode_batch(p["batch"])["records"]
        assert len(recs) == 2 and recs[0][0] == b"network"
        rows = [json.loads(v) for _, v in recs]
        assert rows[0]["byte_tx"] == 5.0 and rows[1]["pod"] == "p2"
        exp.close()


def test_exporter_filters_tables():
    broker = _FakeBroker(0)
    exp = KafkaExporter("127.0.0.1", broker.port, data_sources=("application",))
    exp.export("network", {"time": np.array([1], np.uint32)})
    assert exp.get_counters()["filtered"] == 1
    assert not broker.produced
    exp.close()


def test_acks1_broker_error_counts_as_export_error():
    """A nonzero per-partition error_code must NOT count as success —
    the broker here answers UNKNOWN_TOPIC_OR_PARTITION (3)."""
    broker2 = _FakeBroker.__new__(_FakeBroker)
    broker2.produced = []
    broker2.srv = socket.create_server(("127.0.0.1", 0))
    broker2.port = broker2.srv.getsockname()[1]

    def run_err():
        conn, _ = broker2.srv.accept()
        try:
            while True:
                hdr = _FakeBroker._read(conn, 4)
                if hdr is None:
                    return
                size, = struct.unpack(">i", hdr)
                body = _FakeBroker._read(conn, size)
                p = _parse_produce(hdr + body)
                broker2.produced.append(p)
                topic = p["topic"].encode()
                resp = struct.pack(">ii", p["corr"], 1)
                resp += struct.pack(">h", len(topic)) + topic
                resp += struct.pack(">i", 1)  # one partition
                resp += struct.pack(">ih", 0, 3)  # index, error_code=3
                resp += struct.pack(">qq", -1, -1)
                resp += struct.pack(">i", 0)  # throttle
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except OSError:
            pass

    threading.Thread(target=run_err, daemon=True).start()
    exp = KafkaExporter("127.0.0.1", broker2.port, acks=1)
    exp.export("network", {"time": np.array([1], np.uint32)})
    assert exp.get_counters()["errors"] == 1
    assert exp.get_counters()["batches"] == 0
    exp.close()
    broker2.srv.close()


def test_plugins_cannot_shadow_builtin_protocols(tmp_path):
    from deepflow_tpu.agent.l7.plugins import load_plugins

    (tmp_path / "evil.py").write_text(
        "PROTOCOL = 1\n"
        "def check_payload(p, port=0): return True\n"
        "def parse_payload(p): return None\n"
    )
    assert load_plugins(tmp_path) == []  # proto 1 (HTTP) rejected


def test_config_driven_exporter_construction(tmp_path):
    """server.yaml exporters: section → real sinks at boot (the
    exporters/config seat)."""
    import pytest

    from deepflow_tpu.server.exporters import FileExporter, OtlpExporter
    from deepflow_tpu.server.kafka_exporter import KafkaExporter
    from deepflow_tpu.server.main import Server, build_exporters
    from deepflow_tpu.utils.config import load_config

    cfg, unknown = load_config({
        "storage": {"root": str(tmp_path / "s")},
        "exporters": [
            {"kind": "kafka", "host": "127.0.0.1", "port": 19092,
             "acks": 0, "data_sources": ["network"]},
            {"kind": "otlp", "traces_url": "http://127.0.0.1:1/v1/traces"},
            {"kind": "jsonl", "directory": str(tmp_path / "sink")},
        ],
    })
    assert not unknown
    srv = Server(cfg)  # constructor builds the sinks; no start needed
    kinds = [type(e) for e in srv.exporters]
    assert kinds == [KafkaExporter, OtlpExporter, FileExporter]
    assert srv.exporters[0].addr == ("127.0.0.1", 19092)
    assert srv.exporters[0].data_sources == ("network",)

    with pytest.raises(ValueError):
        build_exporters([{"kind": "nonsense"}])
