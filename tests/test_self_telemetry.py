"""ISSUE 3 pipeline self-telemetry: device counter block, stage spans,
and the dogfooded deepflow_system round trip (counters → store → SQL +
PromQL, bit-exact vs the host-side WindowManager counters)."""

from __future__ import annotations

import math

import numpy as np
import pytest

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
from deepflow_tpu.aggregator.window import (
    CB_LEN,
    CB_STASH_OCCUPANCY,
    CB_VERSION,
    COUNTER_BLOCK_VERSION,
    WindowConfig,
    WindowManager,
)
from deepflow_tpu.datamodel.batch import FlowBatch
from deepflow_tpu.ingest.replay import SyntheticFlowGen
from deepflow_tpu.integration.dfstats import (
    DEEPFLOW_SYSTEM_DB,
    DEEPFLOW_SYSTEM_TABLE,
    points_to_influx,
    system_metric_name,
    system_sink,
)
from deepflow_tpu.querier.engine import QueryEngine
from deepflow_tpu.querier.promql import query_instant
from deepflow_tpu.storage.store import ColumnarStore
from deepflow_tpu.utils.spans import (
    PIPELINE_SPAN_NAMES,
    SPAN_FLUSH_DRAIN,
    SPAN_INGEST_DISPATCH,
    SPAN_STATS_FETCH,
    SPAN_WINDOW_ADVANCE,
    SpanTracer,
)
from deepflow_tpu.utils.stats import StatsCollector, StatsPoint

T0 = 1_700_000_000


def _ingest_some(pipe, n_batches=6, batch=128, seed=3):
    gen = SyntheticFlowGen(num_tuples=200, seed=seed)
    for i in range(n_batches):
        pipe.ingest(FlowBatch.from_records(gen.records(batch, T0 + i)))
    return pipe


# ---------------------------------------------------------------------------
# (1) device counter plane


def test_counter_block_versioned_and_coherent():
    pipe = _ingest_some(
        L4Pipeline(PipelineConfig(window=WindowConfig(capacity=1 << 12),
                                  batch_size=256))
    )
    c = pipe.get_counters()
    # block lanes made it to the host mirror
    assert c["doc_in"] > 0
    assert c["stash_occupancy"] >= 0 and c["stash_evictions"] == 0
    assert c["excess_word_hits"] == 0  # synthetic tags honor the widths
    assert c["window_advances"] > 0
    # the legacy live probes agree with the cached lanes once settled:
    # evictions only move at folds, which run before dispatch
    live = pipe.counters
    assert live["drop_overflow"] == c["stash_evictions"]


def test_snapshot_lanes_ride_the_counter_block():
    """ISSUE 10 (CB v6): snapshot_reads/snapshot_bytes ride the
    EXISTING per-batch fetch — after a snapshot, the next dispatched
    batch's counter block mirrors the host accounting exactly, and the
    snapshot itself shows up in the transfer accounting (2 fetches)."""
    pipe = _ingest_some(
        L4Pipeline(PipelineConfig(
            window=WindowConfig(capacity=1 << 12, min_snapshot_interval=0.0),
            batch_size=256,
        )),
        n_batches=2,
    )
    c0 = pipe.get_counters()
    assert c0["snapshot_reads"] == 0 and c0["device_snapshot_reads"] == 0
    f0 = c0["host_fetches"]
    snap = pipe.snapshot_open()
    assert snap.windows and all(w.partial for w in snap.windows)
    c1 = pipe.get_counters()
    assert c1["snapshot_reads"] == 1 and c1["snapshot_bytes"] > 0
    assert c1["host_fetches"] - f0 <= 2  # the 2-fetch pull-path read
    # device plane still carries the pre-snapshot lanes until the next
    # dispatch ships the rebuilt [reads, bytes] vector
    assert c1["device_snapshot_reads"] == 0
    gen = SyntheticFlowGen(num_tuples=200, seed=3)
    pipe.ingest(FlowBatch.from_records(gen.records(64, T0 + 10)))
    c2 = pipe.get_counters()
    assert c2["device_snapshot_reads"] == c2["snapshot_reads"] == 1
    assert c2["device_snapshot_bytes"] == c2["snapshot_bytes"] > 0


def test_counter_block_rejects_version_drift():
    import jax.numpy as jnp

    wm = WindowManager(WindowConfig(capacity=64))
    bad = jnp.zeros((CB_LEN,), jnp.uint32)  # version lane = 0
    with pytest.raises(ValueError, match="version"):
        wm._process_stats(bad)


def test_counter_block_layout_constants():
    from deepflow_tpu.aggregator.window import (
        CB_FEEDER_SHED,
        CB_FIELDS,
        CB_RING_FILL,
    )

    from deepflow_tpu.aggregator.window import (
        CB_CASCADE_ROWS,
        CB_CASCADE_SHED,
        CB_FOLD_ROWS,
        CB_SKETCH_ROWS,
        CB_SKETCH_SHED,
        CB_SKETCH_POOL_OCC,
        CB_SKETCH_POOL_SPILL,
        CB_SKETCH_PROMOTIONS,
        CB_SNAPSHOT_BYTES,
        CB_SNAPSHOT_READS,
    )

    # layout drift between the device builder and the host parser must
    # fail here, not silently mis-slice (v2 appended the feeder_shed
    # lane, ISSUE 4; v3 appended fold_rows, ISSUE 5; v4 appended the
    # sketch_rows/sketch_shed plane lanes, ISSUE 8; v5 appended the
    # rollup cascade's cascade_rows/cascade_shed lanes, ISSUE 9; v6
    # appended the live read plane's snapshot_reads/snapshot_bytes
    # lanes, ISSUE 10; v7 appended the pooled sketch memory's
    # sketch_pool_spill/sketch_pool_occ/sketch_promotions lanes,
    # ISSUE 20)
    assert CB_VERSION == 0 and CB_LEN == 21
    assert COUNTER_BLOCK_VERSION == 7
    assert CB_STASH_OCCUPANCY == 7
    assert CB_FEEDER_SHED == 10
    assert CB_FOLD_ROWS == 11
    assert CB_SKETCH_ROWS == 12
    assert CB_SKETCH_SHED == 13
    assert CB_CASCADE_ROWS == 14
    assert CB_CASCADE_SHED == 15
    assert CB_SNAPSHOT_READS == 16
    assert CB_SNAPSHOT_BYTES == 17
    assert CB_SKETCH_POOL_SPILL == 18
    assert CB_SKETCH_POOL_OCC == 19
    assert CB_SKETCH_PROMOTIONS == 20
    # the documented field-name table mirrors the index constants
    assert len(CB_FIELDS) == CB_LEN
    assert CB_FIELDS[CB_VERSION] == "version"
    assert CB_FIELDS[CB_STASH_OCCUPANCY] == "stash_occupancy"
    assert CB_FIELDS[CB_RING_FILL] == "ring_fill"
    assert CB_FIELDS[CB_FEEDER_SHED] == "feeder_shed"
    assert CB_FIELDS[CB_FOLD_ROWS] == "fold_rows"
    assert CB_FIELDS[CB_SKETCH_ROWS] == "sketch_rows"
    assert CB_FIELDS[CB_SKETCH_SHED] == "sketch_shed"
    assert CB_FIELDS[CB_CASCADE_ROWS] == "cascade_rows"
    assert CB_FIELDS[CB_CASCADE_SHED] == "cascade_shed"
    assert CB_FIELDS[CB_SNAPSHOT_READS] == "snapshot_reads"
    assert CB_FIELDS[CB_SNAPSHOT_BYTES] == "snapshot_bytes"
    assert CB_FIELDS[CB_SKETCH_POOL_SPILL] == "sketch_pool_spill"
    assert CB_FIELDS[CB_SKETCH_POOL_OCC] == "sketch_pool_occ"
    assert CB_FIELDS[CB_SKETCH_PROMOTIONS] == "sketch_promotions"


# ---------------------------------------------------------------------------
# (2) host stage tracing


def test_spans_cover_pipeline_stages_and_checkpoint(tmp_path):
    from deepflow_tpu.aggregator.checkpoint import save_window_state
    from deepflow_tpu.querier.live import QueryResultCache

    pipe = _ingest_some(
        L4Pipeline(PipelineConfig(window=WindowConfig(capacity=1 << 12),
                                  batch_size=256))
    )
    save_window_state(pipe.wm, tmp_path / "ckpt.npz")
    # the live read plane's stages (ISSUE 10): snapshot_open emits
    # query.snapshot on the pipeline tracer; a result-cache lookup
    # emits query.cache on whatever tracer the cache carries
    pipe.snapshot_open()
    cache = QueryResultCache(max_entries=4, tracer=pipe.tracer)
    assert cache.lookup(("q", "db", "t"), token=1) is None
    summary = pipe.tracer.summary()
    for name in PIPELINE_SPAN_NAMES:
        assert name in summary, f"missing span {name}: {sorted(summary)}"
        assert summary[name]["count"] > 0
        assert summary[name]["total_us"] >= summary[name]["max_us"] >= 0
    # dispatch fires once per non-empty batch; advance strictly fewer
    assert summary[SPAN_INGEST_DISPATCH]["count"] == 6
    assert summary[SPAN_STATS_FETCH]["count"] >= 6
    assert summary[SPAN_WINDOW_ADVANCE]["count"] < 6
    assert summary[SPAN_FLUSH_DRAIN]["count"] >= 1


def test_spans_export_through_otlp_exporter_path():
    """Tracer spans drain through the EXISTING exporter seam: rows land
    on the l7_flow_log traces lane and OtlpExporter._row_to_span turns
    each into a well-formed OTel span."""
    from deepflow_tpu.server.exporters import CallbackExporter, OtlpExporter

    tracer = SpanTracer(service="unit.pipeline")
    with tracer.span(SPAN_INGEST_DISPATCH):
        pass
    with tracer.span(SPAN_FLUSH_DRAIN):
        pass

    seen = []
    exp = CallbackExporter(lambda table, rows: seen.append((table, rows)))
    n = tracer.export_otlp(exp)
    assert n == 2
    table, rows = seen[0]
    assert table == "l7_flow_log"
    assert {r["endpoint"] for r in rows} == {SPAN_INGEST_DISPATCH, SPAN_FLUSH_DRAIN}
    spans = [OtlpExporter._row_to_span(r) for r in rows]
    assert all(s.service == "unit.pipeline" for s in spans)
    assert all(len(s.trace_id) == 32 and len(s.span_id) == 16 for s in spans)
    # drained: a second export ships nothing
    assert tracer.export_otlp(exp) == 0


def test_jit_cache_monitor_counts_compile_then_retrace():
    import jax
    import jax.numpy as jnp

    from deepflow_tpu.utils.spans import JitCacheMonitor

    f = jax.jit(lambda x: x + 1)
    mon = JitCacheMonitor(f)
    f(jnp.ones(4))
    assert mon.get_counters() == {"jit_compiles": 1, "jit_retraces": 0}
    f(jnp.ones(4))  # same shape — cache hit
    assert mon.get_counters() == {"jit_compiles": 1, "jit_retraces": 0}
    f(jnp.ones(5))  # shape leak
    assert mon.get_counters() == {"jit_compiles": 1, "jit_retraces": 1}


# ---------------------------------------------------------------------------
# (3) dogfooding: deepflow_system round trip (the acceptance criterion)


def test_pipeline_counters_roundtrip_sql_and_promql():
    pipe = _ingest_some(
        L4Pipeline(PipelineConfig(window=WindowConfig(capacity=1 << 12),
                                  batch_size=256))
    )
    expected = pipe.get_counters()
    assert expected["doc_in"] > 0 and expected["host_fetches"] > 0

    store = ColumnarStore()
    col = StatsCollector(interval_s=999)
    col.register("tpu_pipeline", pipe, kind="L4Pipeline", interval="1s")
    col.register("tpu_pipeline_spans", pipe.tracer, kind="L4Pipeline")
    col.add_sink(system_sink(store))
    col.tick(now=float(T0 + 100))

    # -- SQL engine over deepflow_system.deepflow_system ---------------
    eng = QueryEngine(store)
    for field in ("doc_in", "flushed_doc", "drop_before_window",
                  "stash_occupancy", "host_fetches", "bytes_fetched",
                  "snapshot_reads", "snapshot_bytes"):
        metric = system_metric_name("tpu_pipeline", field)
        res = eng.execute(
            "SELECT value FROM deepflow_system.deepflow_system "
            f"WHERE metric = '{metric}'"
        )
        assert res.rows == 1, (field, res.rows)
        assert float(res.values["value"][0]) == float(expected[field]), field

    # span aggregates dogfood through the same table
    res = eng.execute(
        "SELECT value FROM deepflow_system.deepflow_system WHERE metric = "
        f"'{system_metric_name('tpu_pipeline_spans', 'ingest.dispatch.count')}'"
    )
    assert res.rows == 1 and float(res.values["value"][0]) == 6.0

    # -- PromQL over the same rows -------------------------------------
    for field in ("doc_in", "window_advances", "bytes_uploaded"):
        out = query_instant(
            store,
            system_metric_name("tpu_pipeline", field) + '{kind="L4Pipeline"}',
            T0 + 101,
            db=DEEPFLOW_SYSTEM_DB,
            table=DEEPFLOW_SYSTEM_TABLE,
        )
        assert len(out) == 1, field
        assert out[0]["labels"]["interval"] == "1s"
        assert out[0]["value"] == float(expected[field]), field


def test_system_sink_skips_nonfinite_and_nonnumeric():
    store = ColumnarStore()
    sink = system_sink(store)
    sink(
        [
            StatsPoint(float(T0), "m", (), {
                "ok": 3, "bad_nan": float("nan"), "bad_inf": float("inf"),
                "name": "not-a-number",
            })
        ]
    )
    rows = store.scan(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE)
    assert list(rows["metric"]) == ["m_ok"]
    assert rows["value"][0] == 3.0


# ---------------------------------------------------------------------------
# satellites: influx typing/escaping + collector source-error policy


def test_points_to_influx_int_typing_and_nonfinite_skip():
    text = points_to_influx(
        [
            StatsPoint(float(T0), "mod", (("a", "x=y\\z, w"),), {
                "n": 7,
                "flag": True,
                "ratio": 0.5,
                "nan": float("nan"),
                "inf": float("-inf"),
            })
        ]
    )
    assert text == (
        f"mod,a=x\\=y\\\\z\\,\\ w n=7i,flag=1i,ratio=0.5 {T0}000000000"
    )
    from deepflow_tpu.integration.formats import parse_influx_lines

    points, errors = parse_influx_lines(text)
    assert errors == 0
    assert points[0].tags == {"a": "x=y\\z, w"}
    assert points[0].fields == {"n": 7.0, "flag": 1.0, "ratio": 0.5}
    assert all(math.isfinite(v) for v in points[0].fields.values())


def test_points_to_influx_numpy_scalars_keep_int_typing():
    text = points_to_influx(
        [StatsPoint(float(T0), "m", (), {"i": np.int64(9), "f": np.float32(2.0)})]
    )
    assert "i=9i" in text and "f=2.0" in text


def test_stats_collector_backs_off_and_reprobes_broken_sources():
    """ISSUE 6: a source that keeps failing enters capped exponential
    backoff (sampled at 1, 2, 4, … tick spacing) instead of being
    dropped forever; when it heals, reporting resumes and the recovery
    is counted once."""
    col = StatsCollector(interval_s=999)

    calls = {"n": 0}
    state = {"fail": True}

    def flaky():
        calls["n"] += 1
        if state["fail"]:
            raise RuntimeError("boom")
        return {"x": 1}

    col.register("bad", flaky)
    col.register("good", lambda: {"x": 1})

    for _ in range(StatsCollector.MAX_SOURCE_FAILURES):
        pts = col.tick(now=float(T0))
        # the healthy source keeps reporting throughout
        assert [p.module for p in pts] == ["good"]
    assert col.n_source_errors == StatsCollector.MAX_SOURCE_FAILURES
    # backoff: the next tick skips the broken source (cooldown=1)...
    col.tick(now=float(T0 + 1))
    assert calls["n"] == StatsCollector.MAX_SOURCE_FAILURES
    # ...but the one after re-probes it — NOT dropped permanently
    col.tick(now=float(T0 + 2))
    assert calls["n"] == StatsCollector.MAX_SOURCE_FAILURES + 1
    assert col.n_source_errors == StatsCollector.MAX_SOURCE_FAILURES + 1
    # the spacing grows (cooldown=2 now) and is capped
    col.tick(now=float(T0 + 3))
    assert calls["n"] == StatsCollector.MAX_SOURCE_FAILURES + 1

    # heal the source: burn through the remaining cooldown, then the
    # re-probe succeeds, reporting resumes, recovery counted once
    state["fail"] = False
    for i in range(4):
        pts = col.tick(now=float(T0 + 4 + i))
        if sorted(p.module for p in pts) == ["bad", "good"]:
            break
    else:
        raise AssertionError("backed-off source never re-probed")
    assert col.n_source_recoveries == 1
    # healthy again: sampled every tick from here on
    pts = col.tick(now=float(T0 + 10))
    assert sorted(p.module for p in pts) == ["bad", "good"]
    assert col.n_source_recoveries == 1


def test_stats_collector_survives_broken_sink():
    """A raising sink callback must not kill the tick (the collector
    thread would die silently) — contained and counted."""
    col = StatsCollector(interval_s=999)
    col.register("m", lambda: {"x": 1})
    col.add_sink(lambda pts: (_ for _ in ()).throw(RuntimeError("sink boom")))
    got = []
    col.add_sink(got.extend)
    pts = col.tick(now=float(T0))
    assert [p.module for p in pts] == ["m"]
    assert col.n_sink_errors == 1
    assert got  # the healthy sink still received the points


def test_stats_collector_transient_failure_recovers():
    col = StatsCollector(interval_s=999)
    state = {"fail": True}

    def flaky():
        if state["fail"]:
            raise RuntimeError("transient")
        return {"x": 2}

    col.register("flaky", flaky)
    col.tick(now=float(T0))  # one failure
    state["fail"] = False
    pts = col.tick(now=float(T0 + 1))  # recovers — failure streak resets
    assert [p.module for p in pts] == ["flaky"]
    assert col.n_source_errors == 1
    state["fail"] = True
    for _ in range(StatsCollector.MAX_SOURCE_FAILURES - 1):
        col.tick(now=float(T0 + 2))
    # streak restarted after recovery: still registered
    assert [p.module for p in col.tick(now=float(T0 + 3))] == []


# ---------------------------------------------------------------------------
# sharded twin: counters + spans + telemetry snapshot shape


def test_sharded_manager_telemetry_snapshot():
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    mesh = make_mesh(2)
    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=6,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
    )
    wm = ShardedWindowManager(ShardedPipeline(mesh, cfg))
    gen = SyntheticFlowGen(num_tuples=100, seed=9)
    for t in (T0, T0 + 1, T0 + 5):
        fb = gen.flow_batch(64, t)
        wm.ingest(fb.tags, fb.meters, fb.valid)
    wm.drain()  # shutdown path must keep the advance-span parity below
    snap = wm.telemetry()
    import json

    json.dumps(snap)  # must be JSON-able as-is (bench snapshot contract)
    assert snap["counters"]["flow_in"] > 0  # pre-fanout flow rows
    assert snap["counters"]["host_fetches"] > 0
    assert snap["counters"]["bytes_uploaded"] > 0
    assert snap["spans"][SPAN_INGEST_DISPATCH]["count"] == 3
    assert SPAN_FLUSH_DRAIN in snap["spans"]
    # ONE window.advance span per advance (the close-before/fold-after
    # split must not double-count) — stage attribution comparable with
    # the single-chip path
    assert (
        snap["spans"][SPAN_WINDOW_ADVANCE]["count"]
        == snap["counters"]["window_advances"]
    )


def test_system_table_labels_not_truncated():
    """Variable-width metric/labels columns: a long packed label string
    must round-trip unclipped (a fixed U<n> would cut it mid-escape and
    PromQL selectors would silently match nothing)."""
    store = ColumnarStore()
    sink = system_sink(store)
    long_val = "v" * 600 + ",x=y"  # > the old U512 clip, with escapables
    sink([StatsPoint(float(T0), "m", (("big", long_val),), {"ok": 1})])
    out = query_instant(
        store, "m_ok", T0 + 1,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE,
    )
    assert len(out) == 1
    assert out[0]["labels"]["big"] == long_val
    assert out[0]["value"] == 1.0


def test_concurrent_sample_pulls_race_tick_thread():
    """ISSUE 18 satellite: the fleet sink makes pull-path `sample()` a
    SECOND consumer of the same counter faces the tick thread reads.
    Hammer both concurrently over healthy, flapping, and broken
    sources: no exception escapes, per-source failure/recovery
    bookkeeping stays consistent (the per-source lock — unlocked
    check-then-act would double-count recoveries or lose failure
    counts), backoff still advances ONLY on ticks, and a healthy
    source's fields are never dropped from a tick snapshot."""
    import threading

    col = StatsCollector()
    calls = {"healthy": 0, "flaky": 0}
    flaky_fail = {"on": False}

    def healthy():
        calls["healthy"] += 1
        return {"v": calls["healthy"]}

    def flaky():
        calls["flaky"] += 1
        if flaky_fail["on"]:
            raise RuntimeError("flap")
        return {"v": 1}

    def broken():
        raise RuntimeError("always")

    col.register("healthy", healthy)
    flaky_src = col.register("flaky", flaky)
    broken_src = col.register("broken", broken)

    stop = threading.Event()
    errors: list[BaseException] = []

    def puller():
        # the fleet exporter's consumption shape: bare sample() pulls
        while not stop.is_set():
            try:
                pts = col.sample(1000.0)
                mods = [p.module for p in pts]
                assert "healthy" in mods
            except BaseException as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=puller) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(60):
            # flap the flaky source on and off while ticks race pulls
            flaky_fail["on"] = (i // 10) % 2 == 1
            pts = col.tick(1000.0 + i)
            assert any(p.module == "healthy" for p in pts)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors

    # bookkeeping consistency under the race: counts are sane (no
    # negative/garbled state), the broken source sits in backoff with
    # a bounded cooldown, and the flaky source ended recovered
    assert col.n_source_errors >= 3  # broken alone guarantees this
    assert 0 <= broken_src.cooldown <= col.MAX_BACKOFF_TICKS
    assert broken_src.failures >= col.MAX_SOURCE_FAILURES
    assert broken_src.suppressed
    flaky_fail["on"] = False
    for i in range(col.MAX_BACKOFF_TICKS + 1):
        col.tick(2000.0 + i)
    assert flaky_src.failures == 0 and not flaky_src.suppressed
    # recoveries never exceed the number of suppression entries — a
    # double-counted recovery is exactly what the per-source lock
    # prevents
    assert col.n_source_recoveries <= col.n_source_errors
