"""eBPF-userspace symbolization: real ELF symtab parsing (pinned
against `nm`), live /proc/self resolution of a libc function address,
JVM perf-map frames, and the continuous-profiler fold→PROFILE-frame
loop feeding the existing flame-query plane."""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import subprocess

import pytest

from deepflow_tpu.agent.symbolizer import (
    ElfSymbols,
    JavaPerfMap,
    ProcMaps,
    ProfileAggregator,
    Symbolizer,
)

C_SRC = r"""
int helper_alpha(int x) { return x + 1; }
int helper_beta(int x) { return helper_alpha(x) * 2; }
int main(void) { return helper_beta(20); }
"""


@pytest.fixture(scope="module")
def tiny_elf(tmp_path_factory):
    d = tmp_path_factory.mktemp("elf")
    src = d / "t.c"
    src.write_text(C_SRC)
    out = d / "t.bin"
    r = subprocess.run(["gcc", "-O0", "-o", str(out), str(src)],
                       capture_output=True)
    if r.returncode != 0:
        pytest.skip(f"gcc unavailable: {r.stderr.decode()[:100]}")
    return str(out)


def test_elf_symbols_match_nm(tiny_elf):
    syms = ElfSymbols.load(tiny_elf)
    names = {n for _, _, n in syms.syms}
    assert {"helper_alpha", "helper_beta", "main"} <= names

    nm = subprocess.run(["nm", "--defined-only", tiny_elf],
                        capture_output=True, text=True)
    if nm.returncode == 0:
        want = {}
        for line in nm.stdout.splitlines():
            parts = line.split()
            if len(parts) == 3 and parts[1] in ("T", "t"):
                want[parts[2]] = int(parts[0], 16)
        for fn in ("helper_alpha", "helper_beta", "main"):
            assert syms.resolve(want[fn]) == fn
            assert syms.resolve(want[fn] + 2) == fn  # inside the body


def test_proc_self_maps_and_libc_resolution():
    maps = ProcMaps.read("self")
    assert maps.ranges, "no executable ranges for self"
    libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6")
    addr = ctypes.cast(libc.printf, ctypes.c_void_p).value
    assert maps.find(addr) is not None

    sym = Symbolizer("self")
    name = sym.resolve(addr)
    # glibc aliases printf; accept any *printf* symbol in a libc module
    assert "printf" in name, name
    assert sym.counters["resolved"] >= 1


def test_symbolizer_fallbacks():
    sym = Symbolizer("self")
    assert sym.resolve(0x10) == "[0x10]"  # unmapped
    r = sym.maps.ranges[0]
    out = sym.resolve(r.start + max(0, r.end - r.start - 1))
    assert out  # mapped but maybe nameless → bracket fallback allowed


def test_java_perf_map(tmp_path):
    pid = 4242
    (tmp_path / f"perf-{pid}.map").write_text(
        "7f0000001000 40 Lcom/shop/Cart;::add\n"
        "7f0000002000 10 Interpreter\n"
        "garbage line\n"
    )
    m = JavaPerfMap.read(pid, str(tmp_path))
    assert m.resolve(0x7F0000001010) == "Lcom/shop/Cart;::add"
    assert m.resolve(0x7F0000001FFF) is None  # past the entry size
    assert m.resolve(0x7F0000002005) == "Interpreter"


def test_profile_aggregator_to_flame_plane(tiny_elf):
    syms = ElfSymbols.load(tiny_elf)
    by_name = {n: a for a, _, n in syms.syms}
    agg = ProfileAggregator(app_service="svc-x", event_type="cpu")
    # stand in a real symbolizer for the fake pid: module-relative ELF
    sym = Symbolizer("self")
    sym.maps = ProcMaps.read("self")
    # feed pre-symbolized + raw-addr stacks into one window
    agg.observe_folded("main;helper_beta;helper_alpha", 90)
    agg.observe_folded("main;helper_beta", 10)
    frame = agg.flush(1_700_000_000)
    assert frame is not None
    head, _, body = frame.decode().partition("\n")
    assert head.split("\x00") == ["svc-x", "cpu", "1700000000"]

    # the frame is exactly what the profile ingest lane accepts
    from deepflow_tpu.integration.formats import parse_folded

    samples, errors = parse_folded(body)
    assert errors == 0
    assert {s.stack: s.value for s in samples} == {
        "main;helper_beta;helper_alpha": 90,
        "main;helper_beta": 10,
    }
    assert agg.flush(0) is None  # window cleared


def test_aggregator_raw_addresses_via_self(tiny_elf):
    """Raw addr stacks through a REAL process symbolizer: use our own
    pid + libc addresses so resolution exercises maps+ELF end-to-end."""
    libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6")
    printf_addr = ctypes.cast(libc.printf, ctypes.c_void_p).value
    malloc_addr = ctypes.cast(libc.malloc, ctypes.c_void_p).value
    agg = ProfileAggregator(app_service="self-prof")
    agg.observe(os.getpid(), [printf_addr, malloc_addr], weight=3)
    frame = agg.flush(1)
    assert frame is not None
    body = frame.decode().split("\n", 1)[1]
    assert "printf" in body and "malloc" in body and body.endswith(" 3")


def test_continuous_profiler_ships_profile_frames():
    """perf-stack samples → ContinuousProfiler → PROFILE frame → the
    server-side profile ingest shape (flame-plane compatible)."""
    import ctypes
    import ctypes.util

    from deepflow_tpu.agent.ebpf_bridge import ContinuousProfiler, PerfStackSample

    sent = []

    class Sender:
        def send(self, b):
            sent.append(b)

    libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6")
    printf_addr = ctypes.cast(libc.printf, ctypes.c_void_p).value
    prof = ContinuousProfiler(Sender(), app_service="svc-prof")
    prof.observe([
        PerfStackSample(os.getpid(), [printf_addr], weight=5),
        PerfStackSample(os.getpid(), [printf_addr], weight=2),
    ])
    frame = prof.flush(1_700_000_000)
    assert frame is not None and sent == [frame]
    head, _, body = frame.decode().partition("\n")
    assert head.startswith("svc-prof\x00cpu\x00")
    assert "printf" in body and body.endswith(" 7")  # merged weights


def test_java_frames_fold_without_separator_corruption(tmp_path):
    """';' in JVM signatures must not split frames in the folded line."""
    from deepflow_tpu.agent.symbolizer import JavaPerfMap, Symbolizer
    from deepflow_tpu.integration.formats import parse_folded

    sym = Symbolizer("self")
    sym.java = JavaPerfMap([(0x1000, 0x100, "Lcom/shop/Cart;::add")])
    folded = sym.fold([0x1010])
    samples, errors = parse_folded(folded + " 4")
    assert errors == 0 and len(samples) == 1
    assert samples[0].stack == "Lcom/shop/Cart:::add"


def test_continuous_profiler_interval_flush():
    from deepflow_tpu.agent.ebpf_bridge import ContinuousProfiler

    prof = ContinuousProfiler(None, interval_s=10.0)
    prof.agg.observe_folded("a;b", 1)
    assert prof.maybe_flush(5.0) is None  # inside the window
    frame = prof.maybe_flush(15.0)
    assert frame is not None
    prof.agg.observe_folded("a;b", 1)
    assert prof.maybe_flush(20.0) is None  # window restarts at 15
    assert prof.maybe_flush(25.0) is not None
