"""Cloud adapters (aliyun/aws) driven by recorded API-response fixtures
through CloudTask → Recorder, and the tagrecorder K8s label/annotation/
env dictionaries (reference: controller/cloud/aliyun/, cloud/aws/,
tagrecorder/ch_pod_k8s_label.go and friends)."""

from __future__ import annotations

import json

import numpy as np

from deepflow_tpu.controller.cloud import CloudTask, KubernetesGather
from deepflow_tpu.controller.cloud_adapters import AliyunPlatform, AwsPlatform
from deepflow_tpu.controller.recorder import Recorder
from deepflow_tpu.controller.resources import ResourceDB
from deepflow_tpu.controller.tagrecorder import TagRecorder
from deepflow_tpu.querier.translation import Translator
from deepflow_tpu.storage.store import ColumnarStore

ALIYUN_FIXTURE = {
    "DescribeRegions": {"Regions": {"Region": [
        {"RegionId": "cn-hangzhou", "LocalName": "华东1"},
    ]}},
    "DescribeZones": {"Zones": {"Zone": [
        {"ZoneId": "cn-hangzhou-h", "RegionId": "cn-hangzhou"},
        {"ZoneId": "cn-hangzhou-i", "RegionId": "cn-hangzhou"},
    ]}},
    "DescribeVpcs": {"Vpcs": {"Vpc": [
        {"VpcId": "vpc-abc", "VpcName": "prod", "CidrBlock": "10.0.0.0/8",
         "RegionId": "cn-hangzhou"},
    ]}},
    "DescribeVSwitches": {"VSwitches": {"VSwitch": [
        {"VSwitchId": "vsw-1", "VpcId": "vpc-abc", "CidrBlock": "10.1.0.0/16",
         "ZoneId": "cn-hangzhou-h", "VSwitchName": "web-tier"},
    ]}},
    "DescribeInstances": {"Instances": {"Instance": [
        {"InstanceId": "i-web1", "InstanceName": "web-1", "Status": "Running",
         "ZoneId": "cn-hangzhou-h",
         "VpcAttributes": {"VpcId": "vpc-abc"}},
    ]}},
    "DescribeNetworkInterfaces": {"NetworkInterfaceSets": {"NetworkInterfaceSet": [
        {"NetworkInterfaceId": "eni-1", "MacAddress": "00:16:3e:aa:bb:cc",
         "VSwitchId": "vsw-1", "VpcId": "vpc-abc", "InstanceId": "i-web1",
         "PrivateIpSets": {"PrivateIpSet": [
             {"PrivateIpAddress": "10.1.2.3", "Primary": True},
         ]}},
    ]}},
}

AWS_FIXTURE = {
    "DescribeRegions": {"Regions": [{"RegionName": "us-east-1"}]},
    "DescribeAvailabilityZones": {"AvailabilityZones": [
        {"ZoneName": "us-east-1a", "RegionName": "us-east-1"},
    ]},
    "DescribeVpcs": {"Vpcs": [
        {"VpcId": "vpc-123", "CidrBlock": "172.31.0.0/16",
         "Tags": [{"Key": "Name", "Value": "main"}]},
    ]},
    "DescribeSubnets": {"Subnets": [
        {"SubnetId": "subnet-9", "VpcId": "vpc-123",
         "CidrBlock": "172.31.1.0/24", "AvailabilityZone": "us-east-1a"},
    ]},
    "DescribeInstances": {"Reservations": [{"Instances": [
        {"InstanceId": "i-0abc", "VpcId": "vpc-123", "SubnetId": "subnet-9",
         "State": {"Name": "running"},
         "Placement": {"AvailabilityZone": "us-east-1a"},
         "Tags": [{"Key": "Name", "Value": "api-server"}],
         "NetworkInterfaces": [
             {"NetworkInterfaceId": "eni-7", "MacAddress": "0a:1b:2c:3d:4e:5f",
              "VpcId": "vpc-123", "SubnetId": "subnet-9",
              "PrivateIpAddresses": [{"PrivateIpAddress": "172.31.1.50"}]},
         ]},
    ]}]},
}


def _settle(task):
    task.poll()  # allocate ids
    return task.poll()  # resolve _refs against them


def test_aliyun_fixture_reconciles():
    rec = Recorder(ResourceDB())
    task = CloudTask(AliyunPlatform(ALIYUN_FIXTURE), rec)
    _settle(task)
    db = rec.db
    assert [r.name for r in db.list("region")] == ["华东1"]
    assert len(db.list("az")) == 2
    assert db.list("l3_epc")[0].name == "prod"
    assert db.list("subnet")[0].attrs["cidr"] == "10.1.0.0/16"
    vm = db.list("device")[0]
    assert vm.name == "web-1" and vm.attrs["type"] == "vm"

    vifs = db.vinterfaces()
    assert len(vifs) == 1
    v = vifs[0]
    assert v["ips"] == ["10.1.2.3"]
    assert v["mac"] == 0x00163EAABBCC
    assert v["epc_id"] == rec.id_of("aliyun", "l3_epc", "vpc-abc")
    assert v["subnet_id"] == rec.id_of("aliyun", "subnet", "vsw-1")
    assert v["l3_device_id"] == rec.id_of("aliyun", "device", "i-web1")


def test_aws_fixture_reconciles():
    rec = Recorder(ResourceDB())
    task = CloudTask(AwsPlatform(AWS_FIXTURE), rec)
    _settle(task)
    db = rec.db
    assert db.list("l3_epc")[0].name == "main"  # Name tag wins
    assert db.list("device")[0].name == "api-server"
    v = db.vinterfaces()[0]
    assert v["ips"] == ["172.31.1.50"]
    assert v["epc_id"] == rec.id_of("aws", "l3_epc", "vpc-123")
    assert v["l3_device_id"] == rec.id_of("aws", "device", "i-0abc")


def test_aliyun_instance_deletion_propagates():
    rec = Recorder(ResourceDB())
    plat = AliyunPlatform(ALIYUN_FIXTURE)
    task = CloudTask(plat, rec)
    _settle(task)
    pruned = json.loads(json.dumps(ALIYUN_FIXTURE))
    pruned["DescribeInstances"]["Instances"]["Instance"] = []
    pruned["DescribeNetworkInterfaces"]["NetworkInterfaceSets"]["NetworkInterfaceSet"] = []
    plat.update(pruned)
    cs = task.poll()
    assert ("device", "i-web1") in cs.deleted
    assert rec.db.list("device") == [] and rec.db.vinterfaces() == []


def _k8s_pod_objects():
    return {
        "nodes": [], "namespaces": [{"metadata": {"name": "default"}}],
        "services": [],
        "pods": [
            {
                "metadata": {
                    "name": "web-0", "namespace": "default",
                    "labels": {"app": "web", "tier": "frontend"},
                    "annotations": {"owner": "team-a"},
                },
                "spec": {
                    "nodeName": "n1",
                    "containers": [
                        {"env": [{"name": "MODE", "value": "prod"},
                                 {"name": "SECRETLESS", "value": "1"}]},
                    ],
                },
                "status": {"podIP": "10.9.0.5"},
            },
            {
                "metadata": {"name": "db-0", "namespace": "default",
                             "labels": {"app": "db"}},
                "spec": {"containers": []},
                "status": {"podIP": "10.9.0.6"},
            },
        ],
    }


def test_tagrecorder_k8s_label_dictionaries():
    rec = Recorder(ResourceDB())
    task = CloudTask(KubernetesGather(_k8s_pod_objects(), epc_id=3), rec)
    _settle(task)
    store = ColumnarStore()
    tr = Translator(store)
    tagrec = TagRecorder(rec.db, store, tr)
    assert tagrec.sync()

    web_id = rec.id_of("k8s", "pod", "k8s/cluster/pod/default/web-0")
    db_id = rec.id_of("k8s", "pod", "k8s/cluster/pod/default/db-0")

    # singular form: one row per (pod, key)
    rows = store.scan("flow_tag", "pod_k8s_label_map")
    by_pod = {}
    for i, k, v in zip(rows["id"], rows["key"], rows["value"]):
        by_pod.setdefault(int(i), {})[str(k)] = str(v)
    assert by_pod[web_id] == {"app": "web", "tier": "frontend"}
    assert by_pod[db_id] == {"app": "db"}

    # plural form: whole dict JSON per pod
    rows = store.scan("flow_tag", "pod_k8s_labels_map")
    plural = {int(i): json.loads(str(v)) for i, v in zip(rows["id"], rows["value"])}
    assert plural[web_id]["tier"] == "frontend"

    # annotations + envs materialize too
    rows = store.scan("flow_tag", "pod_k8s_annotation_map")
    assert {(int(i), str(k), str(v)) for i, k, v in
            zip(rows["id"], rows["key"], rows["value"])} == {(web_id, "owner", "team-a")}
    rows = store.scan("flow_tag", "pod_k8s_env_map")
    envs = {str(k): str(v) for _, k, v in
            zip(rows["id"], rows["key"], rows["value"])}
    assert envs == {"MODE": "prod", "SECRETLESS": "1"}

    # query-time custom-tag lookup (the `k8s.label.<key>` seat)
    out = tr.k8s_meta("label", "app", np.array([web_id, db_id, 999]))
    assert list(out) == ["web", "db", ""]


def test_engine_k8s_label_function():
    from deepflow_tpu.querier import QueryEngine
    from deepflow_tpu.storage.store import ColumnSpec, TableSchema

    rec = Recorder(ResourceDB())
    task = CloudTask(KubernetesGather(_k8s_pod_objects(), epc_id=3), rec)
    _settle(task)
    store = ColumnarStore()
    tr = Translator(store)
    TagRecorder(rec.db, store, tr).sync()
    web_id = rec.id_of("k8s", "pod", "k8s/cluster/pod/default/web-0")
    db_id = rec.id_of("k8s", "pod", "k8s/cluster/pod/default/db-0")

    store.create_table("flow_metrics", TableSchema(
        "application_1s",
        (ColumnSpec("time", "u4"), ColumnSpec("pod_id_0", "u4"),
         ColumnSpec("request", "f4")),
    ))
    store.insert("flow_metrics", "application_1s", {
        "time": np.array([1000, 1000, 1000], np.uint32),
        "pod_id_0": np.array([web_id, db_id, web_id], np.uint32),
        "request": np.array([1, 1, 1], np.float32),
    })
    eng = QueryEngine(store, tr)
    r = eng.execute(
        "select k8s_label(pod_id_0, 'app') as app, Sum(request) as req "
        "from application.1s group by k8s_label(pod_id_0, 'app') order by app"
    )
    assert r.to_dicts() == [{"app": "db", "req": 1.0}, {"app": "web", "req": 2.0}]
