"""Wire protocol round-trips: flow header, frame reassembly, Document pb.

The encode side plays the agent (uniform_sender.rs framing +
document.rs pb serialization); the decode side plays the ingester
(receiver.go + libs/app/codec.go). Round-trip equality across the pair
pins the wire ABI.
"""

import numpy as np
import pytest

from deepflow_tpu.aggregator.fanout import FanoutConfig
from deepflow_tpu.aggregator.pipeline import L4Pipeline, L7Pipeline, PipelineConfig
from deepflow_tpu.aggregator.window import WindowConfig
from deepflow_tpu.datamodel.batch import DocBatch, FlowBatch
from deepflow_tpu.datamodel.code import CodeId, DocumentFlag, MeterId
from deepflow_tpu.datamodel.schema import APP_METER, FLOW_METER, TAG_SCHEMA, USAGE_METER
from deepflow_tpu.ingest.codec import (
    DocumentDecoder,
    encode_docbatch,
    encode_document,
)
from deepflow_tpu.ingest.framing import (
    HEADER_LEN,
    FlowHeader,
    FrameReassembler,
    MessageType,
    encode_frame,
    split_messages,
)
from deepflow_tpu.ingest.replay import SyntheticAppGen, SyntheticFlowGen

_T = TAG_SCHEMA


def test_header_roundtrip():
    h = FlowHeader(
        msg_type=int(MessageType.METRICS),
        team_id=7,
        organization_id=3,
        agent_id=42,
        encoder=0,
    )
    h.frame_size = 119
    raw = h.encode()
    assert len(raw) == HEADER_LEN
    got = FlowHeader.parse(raw)
    assert got == h
    # frame_size is big-endian on the wire (uniform_sender.rs:134)
    assert raw[:4] == (119).to_bytes(4, "big")


def test_frame_roundtrip_and_reassembly():
    msgs = [b"alpha", b"bb", b"x" * 300]
    frame = encode_frame(FlowHeader(msg_type=3, agent_id=5), msgs)
    # single-shot parse
    hdr = FlowHeader.parse(frame[:HEADER_LEN])
    assert hdr.frame_size == len(frame)
    assert split_messages(frame[HEADER_LEN:]) == msgs

    # chunked TCP stream with two frames + garbage prefix
    frame2 = encode_frame(FlowHeader(msg_type=4, agent_id=5), [b"second"])
    stream = b"\xff\x00\x01" + frame + frame2
    ra = FrameReassembler()
    got = []
    for i in range(0, len(stream), 7):
        got += ra.feed(stream[i : i + 7])
    assert len(got) == 2
    assert ra.bad_frames > 0
    assert split_messages(got[0][1]) == msgs
    assert got[1][0].msg_type == 4


def _roundtrip_batch(db: DocBatch):
    msgs = encode_docbatch(db, flags=int(DocumentFlag.PER_SECOND_METRICS))
    dec = DocumentDecoder()
    out = dec.decode(msgs)
    assert dec.decode_errors == 0
    return out


def _pipeline_docs(gen, pipe, n=300, t=1_700_000_000, schema=FLOW_METER):
    batches = []
    recs = gen.records(n, t)
    batches += pipe.ingest(FlowBatch.from_records(recs, schema))
    batches += pipe.drain()
    return [b for b in batches if b.size]


# Tag columns expected to survive the wire. endpoint_hash is re-derived
# from the endpoint string (absent here), tap_side travels explicitly.
_WIRE_TAGS = [
    f.name
    for f in _T.fields
    if f.name not in ("endpoint_hash", "time_span")
]


def _assert_batches_equal(db: DocBatch, decoded):
    assert decoded.tags.shape[0] == int(db.valid.sum())
    # decode preserves message order for a single meter type
    src = db.tags[db.valid]
    src_m = db.meters[db.valid]
    for name in _WIRE_TAGS:
        j = _T.index(name)
        np.testing.assert_array_equal(decoded.tags[:, j], src[:, j], err_msg=name)
    np.testing.assert_allclose(decoded.meters, src_m, err_msg="meters")


def test_l4_document_roundtrip():
    pipe = L4Pipeline(PipelineConfig(window=WindowConfig(capacity=1 << 12), batch_size=512))
    docs = _pipeline_docs(SyntheticFlowGen(num_tuples=40, seed=2), pipe)
    assert docs
    for db in docs:
        out = _roundtrip_batch(db)
        assert set(out) == {int(MeterId.FLOW)}
        _assert_batches_equal(db, out[int(MeterId.FLOW)])


def test_l7_document_roundtrip():
    pipe = L7Pipeline(PipelineConfig(window=WindowConfig(capacity=1 << 12), batch_size=512))
    docs = _pipeline_docs(SyntheticAppGen(num_services=8, seed=2), pipe, schema=APP_METER)
    assert docs
    for db in docs:
        out = _roundtrip_batch(db)
        assert set(out) == {int(MeterId.APP)}
        _assert_batches_equal(db, out[int(MeterId.APP)])


def _manual_doc(meter_id, code_id, **tag_overrides):
    tags = np.zeros(_T.num_fields, dtype=np.uint32)
    tags[_T.index("meter_id")] = int(meter_id)
    tags[_T.index("code_id")] = int(code_id)
    for k, v in tag_overrides.items():
        tags[_T.index(k)] = v
    return tags


def test_ipv6_and_negative_epc_roundtrip():
    tags = _manual_doc(
        MeterId.FLOW,
        CodeId.EDGE_IP_PORT,
        is_ipv6=1,
        ip0_w0=0x20010DB8,
        ip0_w3=0x1,
        ip1_w0=0x20010DB8,
        ip1_w3=0x2,
        l3_epc_id=0xFFFE,  # EPC_INTERNET (-2) sign-folded
        l3_epc_id1=7,
        mac0_hi=0x1234,
        mac0_lo=0x56789ABC,
        direction=1,
        agent_id=9,
    )
    meters = np.zeros(FLOW_METER.num_fields, dtype=np.float32)
    meters[FLOW_METER.index("byte_tx")] = 12345
    msg = encode_document(1_700_000_000, tags, meters)
    out = DocumentDecoder().decode([msg])
    d = out[int(MeterId.FLOW)]
    for name in ("is_ipv6", "ip0_w0", "ip0_w3", "ip1_w0", "ip1_w3", "l3_epc_id", "l3_epc_id1", "mac0_hi", "mac0_lo"):
        assert d.tags[0, _T.index(name)] == tags[_T.index(name)], name
    assert d.meters[0, FLOW_METER.index("byte_tx")] == 12345


def test_usage_meter_roundtrip():
    tags = _manual_doc(MeterId.USAGE, CodeId.ACL, acl_gid=3, server_port=11)
    meters = np.zeros(USAGE_METER.num_fields, dtype=np.float32)
    meters[USAGE_METER.index("packet_rx")] = 77
    meters[USAGE_METER.index("l4_byte_tx")] = 999
    msg = encode_document(100, tags, meters)
    out = DocumentDecoder().decode([msg])
    d = out[int(MeterId.USAGE)]
    assert d.meters[0, USAGE_METER.index("packet_rx")] == 77
    assert d.meters[0, USAGE_METER.index("l4_byte_tx")] == 999
    assert d.tags[0, _T.index("acl_gid")] == 3


def test_strings_interned_and_endpoint_hashed():
    tags = _manual_doc(MeterId.APP, CodeId.SINGLE_IP_PORT_APP, l7_protocol=20, direction=1)
    meters = np.zeros(APP_METER.num_fields, dtype=np.float32)
    meters[APP_METER.index("request")] = 1
    msg = encode_document(
        100, tags, meters, strings={"app_service": "svc-a", "endpoint": "/api/v1/users"}
    )
    dec = DocumentDecoder()
    out = dec.decode([msg, msg])
    d = out[int(MeterId.APP)]
    # same strings → same dictionary ids on both rows
    assert d.service_ids[0, 0] == d.service_ids[1, 0] != 0
    assert d.strings.lookup(int(d.service_ids[0, 0])) == "svc-a"
    assert d.strings.lookup(int(d.service_ids[0, 2])) == "/api/v1/users"
    assert d.tags[0, _T.index("endpoint_hash")] != 0


def test_mixed_meter_types_split():
    flow_tags = _manual_doc(MeterId.FLOW, CodeId.SINGLE_IP_PORT, direction=1)
    app_tags = _manual_doc(MeterId.APP, CodeId.SINGLE_IP_PORT_APP, l7_protocol=20, direction=1)
    m1 = np.zeros(FLOW_METER.num_fields, dtype=np.float32)
    m2 = np.zeros(APP_METER.num_fields, dtype=np.float32)
    msgs = [encode_document(1, flow_tags, m1), encode_document(2, app_tags, m2)]
    out = DocumentDecoder().decode(msgs)
    assert set(out) == {int(MeterId.FLOW), int(MeterId.APP)}


def test_corrupt_document_counted():
    dec = DocumentDecoder()
    out = dec.decode([b"\xff\xff\xff"])
    assert out == {}
    assert dec.decode_errors == 1
