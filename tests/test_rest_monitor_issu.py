"""Controller/querier REST API, store monitor (ckmonitor watermark),
schema ISSU, PromQL query_range, self-profiling endpoints
(VERDICT r3 missing #4/#8/#9 + weak #8)."""

from __future__ import annotations

import json
import time
import urllib.request

import numpy as np
import pytest

from deepflow_tpu.storage.issu import AddColumn, MIGRATIONS, read_version, upgrade
from deepflow_tpu.storage.monitor import StoreMonitor
from deepflow_tpu.storage.store import ColumnSpec, ColumnarStore, TableSchema

T0 = 1_700_000_000


@pytest.fixture()
def srv(tmp_path):
    from deepflow_tpu.server.main import Server
    from deepflow_tpu.utils.config import load_config

    cfg, _ = load_config(
        {
            "receiver": {"tcp_port": 0, "udp_port": 0},
            "ingester": {"n_decoders": 1, "prefer_native": False},
            "storage": {"root": str(tmp_path / "store"), "writer_flush_s": 0.05},
        }
    )
    s = Server(cfg).start()
    yield s
    s.stop()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, json.loads(r.read())


def _post(port, path, obj):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode()
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# -- REST ---------------------------------------------------------------


def test_rest_health_resources_agents(srv):
    port = srv.rest.port
    code, health = _get(port, "/v1/health")
    assert code == 200 and health["status"] == "ok" and health["leader"]

    code, out = _post(port, "/v1/resources/pod", {"id": 7, "name": "web-0", "pod_node_id": 3})
    assert code == 201 and out["name"] == "web-0"
    code, pods = _get(port, "/v1/resources/pod")
    assert code == 200 and pods[0]["id"] == 7

    # delete
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/resources/pod/7", method="DELETE"
    )
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["deleted"] is True

    code, agents = _get(port, "/v1/agents")
    assert code == 200 and agents == []  # nothing connected yet


def test_rest_query_and_prom_range(srv):
    # write prometheus samples via the integration schema directly
    from deepflow_tpu.server.integration import PROM_SCHEMA
    from deepflow_tpu.storage.writer import TableWriter

    w = TableWriter(srv.store, "prometheus", PROM_SCHEMA, flush_interval_s=0.01)
    ts = np.array([T0, T0 + 60, T0 + 120], np.uint32)
    w.put(
        {
            "time": ts,
            "metric": np.array(["up"] * 3),
            "labels": np.array(["job=api"] * 3),
            "value": np.array([1.0, 0.0, 1.0]),
        }
    )
    w.flush()
    port = srv.rest.port
    code, rows = _get(port, f"/v1/prom?query=up&time={T0 + 60}")
    assert code == 200 and rows[0]["value"] == 0.0
    code, series = _get(
        port, f"/v1/prom/range?query=up&start={T0}&end={T0 + 120}&step=60"
    )
    assert code == 200
    assert series[0]["values"] == [[T0, 1.0], [T0 + 60, 0.0], [T0 + 120, 1.0]]

    code, res = _post(port, "/v1/query", {"sql": "SELECT value FROM prometheus.samples"})
    assert code == 200 and len(res["rows"]) == 3
    w.stop()


def test_rest_catalog_endpoints(srv):
    port = srv.rest.port
    code, cat = _get(port, "/v1/query/catalog?table=network")
    assert code == 200 and cat["table"] == "network"
    byname = {m["name"]: m for m in cat["metrics"]}
    assert byname["byte_tx"]["type"] == "counter"
    assert "Apdex" in byname["rtt_max"]["operators"]
    code, tables = _get(port, "/v1/query/tables")
    assert code == 200 and isinstance(tables, dict)


def test_rest_profile_endpoints(srv):
    port = srv.rest.port
    code, stacks = _get(port, "/v1/profile/stacks")
    assert code == 200 and len(stacks) > 1  # several live threads
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/v1/profile/cpu?seconds=0.2"
    ) as r:
        body = r.read().decode()
    assert r.status == 200  # folded lines "stack count"
    for line in body.splitlines():
        assert line.rsplit(" ", 1)[1].isdigit()


def test_rest_follower_rejects_writes(srv):
    srv.election = type("E", (), {"is_leader": staticmethod(lambda: False)})()
    code, out = _post(srv.rest.port, "/v1/resources/pod", {"id": 1, "name": "x"})
    assert code == 421
    srv.election = None


# -- monitor ------------------------------------------------------------


def _mk_table(store, db, table, pids, partition_s=3600):
    schema = TableSchema(
        table, (ColumnSpec("time", "u4"), ColumnSpec("v", "f4")), partition_s=partition_s
    )
    store.create_table(db, schema)
    for pid in pids:
        t = np.full(1000, pid * partition_s + 1, np.uint32)
        store.insert(db, table, {"time": t, "v": np.ones(1000, np.float32)})


def test_monitor_ttl_and_watermark(tmp_path):
    store = ColumnarStore(tmp_path / "s")
    _mk_table(store, "flow_log", "l4_flow_log", [0, 1, 2, 3])
    _mk_table(store, "flow_metrics", "network_1s", [0, 1, 2, 3])
    mon = StoreMonitor(
        store,
        max_bytes=1,  # force watermark pressure
        ttl_hours={("flow_log", "l4_flow_log"): 2},
    )
    now = 4 * 3600
    out = mon.check(now)
    # ttl: flow_log partitions older than 2h from t=4h → pids 0,1 dropped
    assert out["ttl_dropped"] == 2
    # watermark: drops proceed until only live heads remain (1 part per table)
    assert len(store.partitions("flow_log", "l4_flow_log")) == 1
    assert len(store.partitions("flow_metrics", "network_1s")) == 1
    # priority: flow_log must have been drained before flow_metrics —
    # verify by reconstructing drop order is impossible post-hoc, but the
    # newest partition of each table must survive
    assert store.partitions("flow_metrics", "network_1s") == [3]


def test_monitor_priority_prefers_low_value_tables(tmp_path):
    store = ColumnarStore(tmp_path / "s")
    _mk_table(store, "pcap", "pcap", [0, 1, 2])
    _mk_table(store, "flow_metrics", "network_1s", [0, 1, 2])
    mon = StoreMonitor(store, max_bytes=store.disk_bytes() - 1)
    mon.check(0)  # one partition dropped: must come from pcap
    assert len(store.partitions("pcap", "pcap")) == 2
    assert len(store.partitions("flow_metrics", "network_1s")) == 3


# -- ISSU ---------------------------------------------------------------


def test_issu_adds_columns_to_old_store(tmp_path):
    root = tmp_path / "store"
    store = ColumnarStore(root)
    # simulate a round-3 l7_flow_log table (no trace columns)
    old = TableSchema(
        "l7_flow_log",
        (ColumnSpec("time", "u4"), ColumnSpec("trace_id", "U64")),
        partition_s=3600,
    )
    store.create_table("flow_log", old)
    store.insert(
        "flow_log",
        "l7_flow_log",
        {"time": np.array([T0], np.uint32), "trace_id": np.array(["t1"])},
    )
    (root / "schema_version").write_text("1")

    # reopen + upgrade (the Server.start boot path)
    store2 = ColumnarStore(root)
    report = upgrade(store2)
    assert report["applied"] == [2]
    assert read_version(root) == 2
    cols = store2.scan("flow_log", "l7_flow_log")
    assert "parent_span_id" in cols and cols["parent_span_id"][0] == ""
    assert cols["trace_id"][0] == "t1"  # old data intact

    # idempotent: a second upgrade applies nothing
    assert upgrade(store2)["applied"] == []


def test_issu_fresh_store_is_born_at_head(tmp_path):
    store = ColumnarStore(tmp_path / "fresh")
    report = upgrade(store)
    assert report == {"applied": [], "tables_changed": 0}
    assert read_version(tmp_path / "fresh") >= 2
