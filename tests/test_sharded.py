"""Multi-device tests on the 8-way virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from deepflow_tpu.datamodel.batch import FlowBatch
from deepflow_tpu.ingest.replay import SyntheticFlowGen
from deepflow_tpu.ops.hll import hll_estimate, hll_init, hll_update
from deepflow_tpu.ops.hashing import fingerprint64
from deepflow_tpu.parallel.mesh import make_mesh
from deepflow_tpu.parallel.sharded import ShardedConfig, ShardedPipeline


def _batch_for(pipe, n_per_dev):
    gen = SyntheticFlowGen(num_tuples=500, seed=42)
    fb = gen.flow_batch(n_per_dev * pipe.n_devices, 1000)
    return fb


def test_mesh_shapes():
    mesh = make_mesh(8, n_hosts=2)
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("host", "chip")


def test_sharded_step_runs_and_counts_docs():
    mesh = make_mesh(8, n_hosts=2)
    cfg = ShardedConfig(capacity_per_device=1 << 10, num_services=64, hll_precision=8)
    pipe = ShardedPipeline(mesh, cfg)
    stash, sketches = pipe.init_state()

    fb = _batch_for(pipe, 128)
    acc = pipe.init_acc(4 * 128)
    stash, acc, sketches = pipe.step(stash, acc, 0, sketches, fb.tags, fb.meters, fb.valid)
    stash, acc, _fold_rows = pipe.fold(stash, acc)

    # every shard should now hold some valid stash rows
    valid = np.asarray(stash.valid)
    assert valid.shape[0] == 8
    assert (valid.sum(axis=1) > 0).all()
    # total stash docs ≤ 4 per input flow, > 0
    assert 0 < valid.sum() <= 4 * 128 * 8


def test_sharded_total_meters_match_input():
    """Sharding must not lose meter mass: the sum of packet_tx over all
    device stashes for edge docs equals the input sum (each flow emits
    its meter once per doc lane)."""
    from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA

    mesh = make_mesh(8, n_hosts=1)
    cfg = ShardedConfig(capacity_per_device=1 << 12, num_services=64, hll_precision=8)
    pipe = ShardedPipeline(mesh, cfg)
    stash, sketches = pipe.init_state()

    fb = _batch_for(pipe, 64)
    in_pkt_tx = fb.meters[:, FLOW_METER.index("packet_tx")].sum()

    acc = pipe.init_acc(4 * 64)
    stash, acc, sketches = pipe.step(stash, acc, 0, sketches, fb.tags, fb.meters, fb.valid)
    stash, acc, _fold_rows = pipe.fold(stash, acc)

    valid = np.asarray(stash.valid)
    # stash payloads are column-major [D, M, S] / [D, T, S]
    meters = np.transpose(np.asarray(stash.meters), (0, 2, 1))
    tags = np.transpose(np.asarray(stash.tags), (0, 2, 1))
    code_col = TAG_SCHEMA.index("code_id")
    pkt_col = FLOW_METER.index("packet_tx")
    # edge docs with direction0 (lane 2) carry the unreversed meter exactly
    # once per flow → their packet_tx total equals the input total.
    from deepflow_tpu.datamodel.code import CodeId, Direction

    dir_col = TAG_SCHEMA.index("direction")
    total = 0.0
    for d in range(8):
        rows = valid[d]
        is_edge = np.isin(tags[d][:, code_col], (int(CodeId.EDGE_IP_PORT), int(CodeId.EDGE_MAC_IP_PORT)))
        is_c2s = tags[d][:, dir_col] == int(Direction.CLIENT_TO_SERVER)
        total += meters[d][rows & is_edge & is_c2s, pkt_col].sum()
    # flows with direction0 known: all in our generator draw with p=0.9
    gen_dir0 = fb.tags["direction0"] != 0
    expected = fb.meters[gen_dir0, pkt_col].sum()
    assert total == expected


def test_window_close_merges_hll_across_devices():
    mesh = make_mesh(8, n_hosts=2)
    cfg = ShardedConfig(capacity_per_device=1 << 10, num_services=16, hll_precision=12)
    pipe = ShardedPipeline(mesh, cfg)
    stash, sketches = pipe.init_state()

    # ~4000 distinct client ips across all shards, one service
    n = 8 * 512
    rng = np.random.default_rng(7)
    gen = SyntheticFlowGen(num_tuples=4000, seed=9)
    fb = gen.flow_batch(n, 2000)
    # pin all flows to one service key
    fb.tags["l3_epc_id1"][:] = 5
    fb.tags["server_port"][:] = 443

    acc = pipe.init_acc(4 * 512)
    stash, acc, sketches = pipe.step(stash, acc, 0, sketches, fb.tags, fb.meters, fb.valid)
    kept, global_view, pod_1m = pipe.window_close(sketches)

    # ISSUE 8: per-window state is authoritative — the view does NOT
    # reset the local planes (slots reset when their window closes
    # in-step); the first return is the planes unchanged
    np.testing.assert_array_equal(
        np.asarray(kept.hll), np.asarray(sketches.hll)
    )
    # global estimate ≈ distinct client ips
    svc = int((5 * 131 + 443) % 16)
    est_rows = np.asarray(jax.device_get(global_view.hll))
    # replicated across devices: every device's copy must agree
    for d in range(1, 8):
        np.testing.assert_array_equal(est_rows[0], est_rows[d])
    est = float(np.asarray(hll_estimate(jnp.asarray(est_rows[0])))[svc])
    true = len(np.unique(fb.tags["ip0_w3"]))
    assert abs(est - true) / true < 0.1
    # pod-wide 1m view exists and matches global (single window here)
    np.testing.assert_array_equal(np.asarray(pod_1m)[0], est_rows[0])


def _groupby_docs(doc_batches, meter_schema):
    """Reduce DocBatches by (timestamp, tag-row) with the schema's
    SUM/MAX lanes — the cross-shard merge that belongs to the query
    layer, used here to compare partial per-device docs to the oracle."""
    from collections import defaultdict

    sum_mask = meter_schema.sum_mask
    acc = {}
    for db in doc_batches:
        for i in range(db.size):
            if not db.valid[i]:
                continue
            key = (int(db.timestamp[i]), tuple(int(x) for x in db.tags[i]))
            m = db.meters[i].astype(np.float64)
            if key in acc:
                prev = acc[key]
                acc[key] = np.where(sum_mask, prev + m, np.maximum(prev, m))
            else:
                acc[key] = m
    return acc


def test_sharded_doc_flush_matches_single_device_oracle():
    """Flushed docs from the 8-device mesh, re-merged by key, must equal
    the single-device RollupPipeline's output on the same stream."""
    from deepflow_tpu.aggregator.pipeline import PipelineConfig, RollupPipeline
    from deepflow_tpu.aggregator.window import WindowConfig
    from deepflow_tpu.datamodel.schema import FLOW_METER
    from deepflow_tpu.parallel.sharded import ShardedWindowManager

    mesh = make_mesh(8, n_hosts=2)
    cfg = ShardedConfig(capacity_per_device=1 << 11, num_services=16, hll_precision=8)
    pipe = ShardedPipeline(mesh, cfg)
    swm = ShardedWindowManager(pipe)

    single = RollupPipeline(
        PipelineConfig(window=WindowConfig(capacity=1 << 14), batch_size=512)
    )

    gen = SyntheticFlowGen(num_tuples=300, seed=11)
    t0 = 5000
    sharded_docs, single_docs = [], []
    from deepflow_tpu.datamodel.batch import FlowBatch

    for t in (t0, t0, t0 + 1, t0 + 2, t0 + 8):
        fb = gen.flow_batch(512, t)
        sharded_docs += swm.ingest(fb.tags, fb.meters, fb.valid)
        single_docs += single.ingest(
            FlowBatch(tags=fb.tags, meters=fb.meters, valid=fb.valid)
        )
    sharded_docs += swm.drain()
    single_docs += single.drain()

    a = _groupby_docs(sharded_docs, FLOW_METER)
    b = _groupby_docs(single_docs, FLOW_METER)
    assert a.keys() == b.keys()
    assert len(a) > 0
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-5)


def test_sharded_growing_batch_keeps_accumulated_rows():
    """Regression twin of test_window_manager_growing_batch_keeps_accumulated_rows
    for the sharded manager: a batch bigger than the per-device ring must
    fold pending rows before replacing it, on every device."""
    from deepflow_tpu.parallel.sharded import ShardedWindowManager

    mesh = make_mesh(8, n_hosts=2)
    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=8,
        accum_batches=2,
    )
    pipe = ShardedPipeline(mesh, cfg)
    swm = ShardedWindowManager(pipe)

    gen = SyntheticFlowGen(num_tuples=5000, seed=13)
    t0 = 7000
    fb_small = gen.flow_batch(8 * 8, t0)  # sizes ring at 2×32 rows/device
    fb_big = gen.flow_batch(8 * 64, t0)  # 256 rows/device > ring → re-init
    docs = []
    docs += swm.ingest(fb_small.tags, fb_small.meters, fb_small.valid)
    docs += swm.ingest(fb_big.tags, fb_big.meters, fb_big.valid)
    fb_tick = gen.flow_batch(8, t0 + 10)  # close window t0
    docs += swm.ingest(fb_tick.tags, fb_tick.meters, fb_tick.valid)

    # single-device oracle over the identical stream
    from deepflow_tpu.aggregator.pipeline import PipelineConfig, RollupPipeline
    from deepflow_tpu.aggregator.window import WindowConfig
    from deepflow_tpu.datamodel.schema import FLOW_METER

    single = RollupPipeline(
        PipelineConfig(window=WindowConfig(capacity=1 << 14), batch_size=512)
    )
    sdocs = []
    for fb in (fb_small, fb_big, fb_tick):
        sdocs += single.ingest(FlowBatch(tags=fb.tags, meters=fb.meters, valid=fb.valid))

    a = _groupby_docs(docs, FLOW_METER)
    b = _groupby_docs(sdocs, FLOW_METER)
    assert len(a) > 0 and a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-5)


def test_hll_sharded_equals_single_device():
    """pmax of per-shard HLL planes == HLL of the concatenated stream."""
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 3000, size=(4096, 1), dtype=np.uint32)
    hi, lo = fingerprint64(jnp.asarray(ids))
    gid = jnp.zeros(4096, jnp.int32)
    ref = hll_update(hll_init(1, 10), gid, hi, lo, jnp.ones(4096, bool))

    merged = np.zeros_like(np.asarray(ref))
    for s in range(8):
        sl = slice(s * 512, (s + 1) * 512)
        part = hll_update(hll_init(1, 10), gid[sl], hi[sl], lo[sl], jnp.ones(512, bool))
        merged = np.maximum(merged, np.asarray(part))
    np.testing.assert_array_equal(merged, np.asarray(ref))


def test_sharded_prereduce_matches_single_device_oracle():
    """Same 8-device vs single-device equality with the batch-local
    pre-reduce on (ShardedConfig.batch_unique_cap, PERF.md §7)."""
    from deepflow_tpu.aggregator.pipeline import PipelineConfig, RollupPipeline
    from deepflow_tpu.aggregator.window import WindowConfig
    from deepflow_tpu.datamodel.batch import FlowBatch
    from deepflow_tpu.datamodel.schema import FLOW_METER
    from deepflow_tpu.parallel.sharded import ShardedWindowManager

    mesh = make_mesh(8, n_hosts=2)
    cfg = ShardedConfig(
        capacity_per_device=1 << 11, num_services=16, hll_precision=8,
        batch_unique_cap=256,  # 300 tuples / 8 devices → plenty of headroom
    )
    pipe = ShardedPipeline(mesh, cfg)
    swm = ShardedWindowManager(pipe)

    single = RollupPipeline(
        PipelineConfig(window=WindowConfig(capacity=1 << 14), batch_size=512)
    )

    gen = SyntheticFlowGen(num_tuples=300, seed=11)
    t0 = 5000
    sharded_docs, single_docs = [], []
    for t in (t0, t0, t0 + 1, t0 + 2, t0 + 8):
        fb = gen.flow_batch(512, t)
        sharded_docs += swm.ingest(fb.tags, fb.meters, fb.valid)
        single_docs += single.ingest(
            FlowBatch(tags=fb.tags, meters=fb.meters, valid=fb.valid)
        )
    sharded_docs += swm.drain()
    single_docs += single.drain()

    a = _groupby_docs(sharded_docs, FLOW_METER)
    b = _groupby_docs(single_docs, FLOW_METER)
    assert a.keys() == b.keys()
    assert len(a) > 0
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-5)
