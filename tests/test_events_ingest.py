"""event / app_log / pcap ingesters: frame-in → queryable-table tests
(VERDICT r3 missing #3; reference: server/ingester/{event,app_log,pcap})."""

from __future__ import annotations

import json
import struct
import time

import numpy as np

from deepflow_tpu.ingest.framing import FlowHeader, MessageType, encode_frame
from deepflow_tpu.ingest.receiver import Receiver
from deepflow_tpu.ingest.sender import UniformSender
from deepflow_tpu.server.events import EventIngester
from deepflow_tpu.storage.store import ColumnarStore

T0 = 1_700_000_000


def _wait(cond, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def _stack():
    recv = Receiver()
    recv.start()
    store = ColumnarStore()
    ing = EventIngester(recv, store, writer_args={"flush_interval_s": 0.05})
    return recv, store, ing


def _send(recv, mt, msgs, agent_id=5, org=1):
    snd = UniformSender(
        [("127.0.0.1", recv.tcp_port)], mt,
        agent_id=agent_id, organization_id=org,
        prefer_native_queue=False, flush_interval=0.05,
    )
    snd.send(msgs)
    snd.close()


def test_proc_and_k8s_events_to_table():
    recv, store, ing = _stack()
    try:
        proc = {
            "time": T0, "start_time_us": T0 * 10**6, "end_time_us": T0 * 10**6 + 500,
            "event_type": "io_write", "process_kname": "nginx",
            "gprocess_id": 42, "description": "slow write",
        }
        k8s = {
            "time": T0 + 1, "event_type": "create",
            "resource_type": "pod", "resource_id": 9,
            "resource_name": "web-0",
        }
        _send(recv, MessageType.PROC_EVENT, [json.dumps(proc).encode()])
        _send(recv, MessageType.K8S_EVENT, [json.dumps(k8s).encode()])
        assert _wait(lambda: ing.get_counters()["rows_written"] >= 2)
        ing.flush()
        rows = store.scan("event", "event")
        assert len(rows["time"]) == 2
        by_type = {t: i for i, t in enumerate(rows["event_type"])}
        assert rows["process_kname"][by_type["io_write"]] == "nginx"
        assert rows["signal_source"][by_type["io_write"]] == 1
        assert rows["resource_name"][by_type["create"]] == "web-0"
        assert rows["agent_id"][0] == 5
    finally:
        ing.stop()
        recv.stop()


def test_alert_events_to_table():
    recv, store, ing = _stack()
    try:
        alert = {
            "time": T0, "policy_id": 3, "policy_name": "high-rtt",
            "level": 3, "target_tags": {"pod": "web-0"},
            "metric_value": 812.5, "description": "rtt over threshold",
        }
        _send(recv, MessageType.ALERT_EVENT, [json.dumps(alert).encode()])
        assert _wait(lambda: ing.get_counters()["rows_written"] >= 1)
        ing.flush()
        rows = store.scan("event", "alert_event")
        assert rows["policy_name"][0] == "high-rtt"
        assert rows["metric_value"][0] == 812.5
        assert json.loads(rows["target_tags"][0]) == {"pod": "web-0"}
    finally:
        ing.stop()
        recv.stop()


def test_app_log_to_table_and_severity_mapping():
    recv, store, ing = _stack()
    try:
        logs = [
            {"timestamp_us": T0 * 10**6, "app_service": "checkout",
             "severity_text": "ERROR", "body": "payment failed",
             "trace_id": "t1", "span_id": "s1", "attributes": {"k": "v"}},
            {"timestamp_us": T0 * 10**6 + 1, "app_service": "checkout",
             "severity_number": 9, "body": "ok"},
        ]
        _send(recv, MessageType.APPLICATION_LOG,
              [json.dumps(l).encode() for l in logs])
        assert _wait(lambda: ing.get_counters()["rows_written"] >= 2)
        ing.flush()
        rows = store.scan("application_log", "log")
        assert len(rows["time"]) == 2
        i = int(np.nonzero(rows["body"] == "payment failed")[0][0])
        assert rows["severity_number"][i] == 17  # "error" mapped
        assert rows["trace_id"][i] == "t1"
        assert rows["app_service"][i] == "checkout"
    finally:
        ing.stop()
        recv.stop()


def test_raw_pcap_to_table():
    recv, store, ing = _stack()
    try:
        pkt = bytes(range(64))
        msg = struct.pack(">QQI", 0xAABBCCDD00112233, T0 * 10**6 + 7, len(pkt)) + pkt
        _send(recv, MessageType.RAW_PCAP, [msg])
        assert _wait(lambda: ing.get_counters()["rows_written"] >= 1)
        ing.flush()
        rows = store.scan("pcap", "pcap")
        assert rows["flow_id_hi"][0] == 0xAABBCCDD
        assert rows["flow_id_lo"][0] == 0x00112233
        assert rows["ts_us"][0] == T0 * 10**6 + 7
        assert bytes.fromhex(rows["packet"][0]) == pkt
    finally:
        ing.stop()
        recv.stop()


def test_malformed_event_counted_not_fatal():
    recv, store, ing = _stack()
    try:
        _send(recv, MessageType.PROC_EVENT, [b"not json"])
        good = {"time": T0, "event_type": "x"}
        _send(recv, MessageType.PROC_EVENT, [json.dumps(good).encode()])
        assert _wait(lambda: ing.get_counters()["rows_written"] >= 1)
        assert ing.get_counters()["decode_errors"] >= 1
    finally:
        ing.stop()
        recv.stop()


def test_syslog_and_agent_log_to_application_log():
    """SYSLOG/AGENT_LOG frames (droplet-message types 1/18) land in the
    application_log table with RFC 3164 <PRI> severity decoded."""
    recv, store, ing = _stack()
    try:
        _send(recv, MessageType.SYSLOG, [b"<11>host app: disk read failure"])
        _send(recv, MessageType.AGENT_LOG, [b"dispatcher: rx ring resized"])
        assert _wait(lambda: ing.get_counters()["rows_written"] >= 2)
        ing.flush()
        cols = store.scan("application_log", "log",
                          columns=["app_service", "severity_text", "body"])
        rows = {str(s): (str(sev), str(b)) for s, sev, b in
                zip(cols["app_service"], cols["severity_text"], cols["body"])}
        assert rows["syslog"] == ("error", "host app: disk read failure")
        assert rows["deepflow-agent"][0] == "info"
        assert "rx ring" in rows["deepflow-agent"][1]
    finally:
        ing.stop()
        recv.stop()


def test_syslog_event_time_preserved():
    """Buffered/relayed lines keep their embedded event time (RFC 5424
    and RFC 3164 heads); lines without one get ingest time."""
    from deepflow_tpu.server.events import EventIngester

    ts, rest = EventIngester._syslog_timestamp("1 2026-07-30T06:12:33.5Z host app: boom")
    assert rest == "host app: boom"
    assert ts == 1_785_391_953_500_000

    # RFC 3164 heads are tz-ambiguous → untouched, caller stamps ingest time
    ts2, rest2 = EventIngester._syslog_timestamp("Jul 30 06:12:33 host app: boom")
    assert ts2 == 0 and rest2 == "Jul 30 06:12:33 host app: boom"

    ts3, rest3 = EventIngester._syslog_timestamp("no timestamp here")
    assert ts3 == 0 and rest3 == "no timestamp here"
