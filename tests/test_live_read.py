"""ISSUE 10 live query plane: open-window snapshot read path + overlay
+ result cache.

Consistency contract, pinned here: (1) interleaved `snapshot_open()`
calls NEVER perturb the stream — flushed output with snapshots is
bit-exact equal to the no-snapshot oracle for any advance interleaving
(seeded fuzz, fold modes full+merge, stats_ring 1+K, single-chip AND
sharded); (2) a window's snapshot rows, overlay-merged with its later
flushed rows (flushed SUPERSEDES partials — the querier's rule), equal
the flushed-only oracle bit-exact; and for a window whose traffic has
quiesced, the snapshot IS the later flush, row for row. (3) The PromQL
overlay returns open-window rows marked partial whose values pin
bit-exact against the same window's post-flush values, unmarked. (4)
The result cache hits on repeats, invalidates on window close (store
epoch moves), evicts LRU at its bound, and its counters dogfood into
deepflow_system like every other Countable.
"""

from __future__ import annotations

import numpy as np
import pytest

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
from deepflow_tpu.aggregator.window import WindowConfig, WindowManager
from deepflow_tpu.datamodel.batch import FlowBatch
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ingest.replay import SyntheticFlowGen
from deepflow_tpu.integration.dfstats import (
    DEEPFLOW_SYSTEM_DB,
    DEEPFLOW_SYSTEM_TABLE,
    LIVE_METRIC_FLOW_BYTES,
    PipelineLiveSource,
    ensure_system_table,
    flow_window_sink,
    live_system_source,
)
from deepflow_tpu.querier.live import LiveRegistry, QueryResultCache, cache_token
from deepflow_tpu.querier.promql import query_instant, query_range
from deepflow_tpu.storage.store import ColumnarStore

T0 = 1_700_000_000


def _pipe(**wkw):
    wkw.setdefault("capacity", 1 << 12)
    wkw.setdefault("min_snapshot_interval", 0.0)
    return L4Pipeline(
        PipelineConfig(window=WindowConfig(**wkw), batch_size=256)
    )


def _db_sig(db):
    return (int(db.timestamp[0]), db.size, db.tags.tobytes(), db.meters.tobytes())


def _win_sig(f):
    return (
        f.window_idx, f.count, f.key_hi.tobytes(), f.key_lo.tobytes(),
        f.tags.tobytes(), f.meters.tobytes(),
    )


# ---------------------------------------------------------------------------
# (1) + (2): consistency pins


def test_snapshot_of_quiesced_window_equals_later_flush():
    """All of a window's rows ingested → snapshot → advance: the
    snapshot rows ARE the flushed rows, bit-exact including order."""
    pipe = _pipe()
    gen = SyntheticFlowGen(num_tuples=200, seed=3)
    for i in range(3):
        pipe.ingest(FlowBatch.from_records(gen.records(128, T0 + i)))
    snap = {w.window_idx: w for w in pipe.snapshot_open().windows}
    assert snap and all(w.partial for w in snap.values())
    # jump far enough that every snapshotted window closes
    flushed = pipe.wm.ingest(
        np.asarray([T0 + 50], np.uint32),
        np.zeros(1, np.uint32), np.zeros(1, np.uint32),
        np.zeros((TAG_SCHEMA.num_fields, 1), np.uint32),
        np.zeros((FLOW_METER.num_fields, 1), np.float32),
        np.ones(1, bool),
    )
    closed = {f.window_idx: f for f in flushed if f.count}
    assert set(snap) <= set(closed)
    for w, s in snap.items():
        f = closed[w]
        assert not f.partial and s.partial
        assert _win_sig(f) == _win_sig(s), w  # bit-exact, order included


@pytest.mark.parametrize("fold_mode", ["full", "merge"])
@pytest.mark.parametrize("stats_ring", [1, 4])
def test_snapshot_interleaving_never_perturbs_the_stream(fold_mode, stats_ring):
    """Seeded fuzz (the test_merge_fold stance): identical streams with
    and without interleaved snapshots produce identical flushed
    DocBatches, and the overlay rule (flushed supersedes a window's
    partials) reproduces the flushed-only oracle exactly."""
    rng = np.random.default_rng(1234 + stats_ring)
    gen_a = SyntheticFlowGen(num_tuples=300, seed=7)
    gen_b = SyntheticFlowGen(num_tuples=300, seed=7)
    live = _pipe(fold_mode=fold_mode, stats_ring=stats_ring, delay=3)
    oracle = _pipe(fold_mode=fold_mode, stats_ring=stats_ring, delay=3)

    t = T0
    out_live, out_oracle = [], []
    last_snapshot = {}
    for step in range(14):
        # random walk with occasional multi-window jumps + a stall
        t += int(rng.choice([0, 1, 1, 2, 7]))
        n = int(rng.integers(16, 200))
        out_live += [_db_sig(d) for d in live.ingest(
            FlowBatch.from_records(gen_a.records(n, t)))]
        out_oracle += [_db_sig(d) for d in oracle.ingest(
            FlowBatch.from_records(gen_b.records(n, t)))]
        if rng.random() < 0.5:
            snap = live.snapshot_open(force=True)
            last_snapshot = {w.window_idx: w for w in snap.windows}
    out_live += [_db_sig(d) for d in live.drain()]
    out_oracle += [_db_sig(d) for d in oracle.drain()]
    assert out_live == out_oracle, (fold_mode, stats_ring)
    # counters that define the stream are untouched too
    cl, co = live.get_counters(), oracle.get_counters()
    for k in ("doc_in", "flushed_doc", "drop_before_window", "stash_evictions"):
        assert cl[k] == co[k], k
    assert cl["snapshot_reads"] > 0 and co["snapshot_reads"] == 0
    assert cl["jit_retraces"] == 0
    # overlay rule (the querier's merge): flushed SUPERSEDES a window's
    # partial snapshot. After the drain every snapshotted window has
    # flushed, so overlay-merging the last snapshot's partials with the
    # flushed stream reproduces the flushed-only oracle exactly.
    flushed_by_start = {sig[0]: sig for sig in out_oracle}
    interval = oracle.config.window.interval
    merged = {
        w * interval: ("partial", s.count) for w, s in last_snapshot.items()
    }
    for sig in out_live:
        merged[sig[0]] = sig  # flushed replaces any partial for its window
    assert merged == flushed_by_start


@pytest.mark.parametrize("n_dev", [1, 2])
def test_sharded_snapshot_consistency(n_dev):
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    def build():
        mesh = make_mesh(n_dev)
        cfg = ShardedConfig(
            capacity_per_device=1 << 10, num_services=16, hll_precision=6,
            hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
        )
        return ShardedWindowManager(
            ShardedPipeline(mesh, cfg), min_snapshot_interval=0.0
        )

    rng = np.random.default_rng(99)
    gen_a = SyntheticFlowGen(num_tuples=100, seed=9)
    gen_b = SyntheticFlowGen(num_tuples=100, seed=9)
    live, oracle = build(), build()
    t = T0
    out_live, out_oracle = [], []
    quiesced_snap = None
    for step in range(8):
        t += int(rng.choice([0, 1, 2, 6]))
        n = 32 * n_dev
        fa, fb = gen_a.flow_batch(n, t), gen_b.flow_batch(n, t)
        out_live += [_db_sig(d) for d in live.ingest(fa.tags, fa.meters, fa.valid)]
        out_oracle += [_db_sig(d) for d in oracle.ingest(fb.tags, fb.meters, fb.valid)]
        if rng.random() < 0.6:
            quiesced_snap = live.snapshot_open(force=True)
    snap = {w.window_idx: w for w in live.snapshot_open(force=True).windows}
    out_live += [_db_sig(d) for d in live.drain()]
    out_oracle += [_db_sig(d) for d in oracle.drain()]
    assert out_live == out_oracle
    assert live.get_counters()["snapshot_reads"] > 0
    # the final pre-drain snapshot covered exactly the still-open span,
    # and each of its windows' rows match the drained rows bit-exact
    drained = {sig[0] // live.interval: sig for sig in out_live}
    for w, s in snap.items():
        sig = drained[w]
        assert s.count == sig[1]
        assert s.tags.tobytes() == sig[2] and s.meters.tobytes() == sig[3]


# ---------------------------------------------------------------------------
# (3): PromQL overlay — the acceptance pin


def _doc_ingest(wm: WindowManager, t: int, keys: list[int], byte_tx: float):
    n = len(keys)
    ts = np.full(n, t, np.uint32)
    hi = np.asarray(keys, np.uint32)
    lo = np.asarray(keys, np.uint32) + 1
    tags = np.zeros((TAG_SCHEMA.num_fields, n), np.uint32)
    meters = np.zeros((FLOW_METER.num_fields, n), np.float32)
    meters[FLOW_METER.index("byte_tx")] = byte_tx
    return wm.ingest(ts, hi, lo, tags, meters, np.ones(n, bool))


def test_promql_range_ending_now_returns_open_window_partial_bit_exact():
    """THE acceptance criterion: a query_range whose range ends 'now'
    returns rows from the currently open window marked partial; after
    the window flushes, the same query returns the SAME values
    unmarked."""
    store = ColumnarStore()
    ensure_system_table(store)
    reg = LiveRegistry()
    wm = WindowManager(WindowConfig(capacity=1 << 10, min_snapshot_interval=0.0))
    reg.register(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, PipelineLiveSource(wm))
    sink = flow_window_sink(store)

    flushed = []
    flushed += _doc_ingest(wm, T0, [10, 20], 100.0)
    flushed += _doc_ingest(wm, T0 + 1, [10], 7.0)
    # windows T0, T0+1 are open; range ends "now" (T0+1)
    live_out = query_range(
        store, LIVE_METRIC_FLOW_BYTES, T0, T0 + 1, 1,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE,
        live=reg, cache=False, lookback_s=1,
    )
    assert live_out, "open windows invisible — the blind spot is back"
    assert all(s.get("partial") for s in live_out)
    live_vals = {
        tuple(sorted(s["labels"].items())): s["values"] for s in live_out
    }
    # byte_tx sums for key 10: window T0 = 100, window T0+1 = 7
    by_key = {s["labels"]["key"]: s for s in live_out
              if s["labels"]["window"] == str(T0)}
    assert by_key[f"{10:08x}{11:08x}"]["values"][0][1] == 100.0

    # close everything; flushed rows land in the store via the SAME row
    # builder the live source used
    flushed += wm.flush_all()
    sink([f for f in flushed if f.count])
    closed_out = query_range(
        store, LIVE_METRIC_FLOW_BYTES, T0, T0 + 1, 1,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE,
        live=reg, cache=False, lookback_s=1,
    )
    closed_vals = {
        tuple(sorted(s["labels"].items())): s["values"] for s in closed_out
    }
    assert not any(s.get("partial") for s in closed_out)
    assert closed_vals == live_vals  # bit-exact across the close


def test_promql_flushed_supersedes_partial_on_growth():
    """Rows arriving AFTER the snapshot are invisible to the partial
    but present post-flush — the flushed sample must supersede the
    stale partial at the same (series, time)."""
    store = ColumnarStore()
    ensure_system_table(store)
    reg = LiveRegistry()
    wm = WindowManager(WindowConfig(capacity=1 << 10, min_snapshot_interval=0.0))
    src = PipelineLiveSource(wm)
    reg.register(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, src)

    _doc_ingest(wm, T0, [10], 100.0)
    wm.snapshot_open(force=True)
    out1 = query_instant(
        store, LIVE_METRIC_FLOW_BYTES, T0 + 1,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE, live=reg,
        lookback_s=2,
    )
    assert out1[0]["value"] == 100.0 and out1[0].get("partial")
    flushed = _doc_ingest(wm, T0, [10], 50.0)  # same window, more bytes
    flushed += wm.flush_all()
    flow_window_sink(store)([f for f in flushed if f.count])
    out2 = query_instant(
        store, LIVE_METRIC_FLOW_BYTES, T0 + 1,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE, live=reg,
        lookback_s=2,
    )
    # flushed value (150) wins over any stale partial (100)
    assert out2[0]["value"] == 150.0
    assert not out2[0].get("partial")


def test_live_system_source_sub_tick_counters():
    """Dogfood: CURRENT Countable values answer a PromQL query without
    waiting for a collector tick or writing the store."""
    from deepflow_tpu.utils.stats import StatsCollector

    store = ColumnarStore()
    ensure_system_table(store)
    reg = LiveRegistry()
    col = StatsCollector(interval_s=999)
    state = {"pumps": 3}
    col.register("tpu_feeder", lambda: dict(state), name="live")
    _, handle = live_system_source(col, registry=reg)

    out = query_instant(
        store, 'tpu_feeder_pumps{name="live"}', T0,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE, live=reg,
    )
    assert len(out) == 1 and out[0]["value"] == 3.0 and out[0]["partial"]
    state["pumps"] = 9  # counters moved — the next pull sees it NOW
    out = query_instant(
        store, 'tpu_feeder_pumps{name="live"}', T0,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE, live=reg,
    )
    assert out[0]["value"] == 9.0
    assert store.row_count(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE) == 0


# ---------------------------------------------------------------------------
# (4): result cache


def _samples_store():
    from deepflow_tpu.storage.store import ColumnSpec, TableSchema

    store = ColumnarStore()
    store.create_table(
        "prometheus",
        TableSchema("samples", (
            ColumnSpec("time", "u4"), ColumnSpec("metric", "O"),
            ColumnSpec("labels", "O"), ColumnSpec("value", "f8"),
        )),
    )
    return store


def _insert_samples(store, t, metric, value):
    store.insert("prometheus", "samples", {
        "time": np.asarray([t], np.uint32),
        "metric": np.asarray([metric], object),
        "labels": np.asarray(["job=api"], object),
        "value": np.asarray([value], np.float64),
    })


def test_result_cache_hit_miss_invalidate_evict():
    store = _samples_store()
    _insert_samples(store, T0, "m", 1.0)
    cache = QueryResultCache(max_entries=2)
    reg = LiveRegistry()

    kw = dict(db="prometheus", table="samples", live=reg, cache=cache)
    r1 = query_range(store, "m", T0, T0 + 2, 1, **kw)
    assert cache.get_counters()["misses"] == 1
    r2 = query_range(store, "m", T0, T0 + 2, 1, **kw)
    assert r2 == r1
    assert cache.get_counters()["hits"] == 1

    # window close = insert = store epoch moves = stale entry dropped
    _insert_samples(store, T0 + 1, "m", 5.0)
    r3 = query_range(store, "m", T0, T0 + 2, 1, **kw)
    c = cache.get_counters()
    assert c["invalidations"] == 1 and c["misses"] == 2
    assert r3 != r1  # recomputed over the new rows
    assert query_range(store, "m", T0, T0 + 2, 1, **kw) == r3
    assert cache.get_counters()["hits"] == 2

    # LRU bound: a dashboard storm of distinct queries cannot grow memory
    for q in range(5):
        query_range(store, "m", T0, T0 + 2 + q, 1, **kw)
    c = cache.get_counters()
    assert c["entries"] <= 2 and c["evictions"] >= 3

    # live epoch moves also invalidate: register a provider whose epoch
    # ticks per pull (counter-style source)
    class Src:
        n = 0

        def __call__(self, lo, hi):
            return None

        def epoch(self):
            Src.n += 1
            return Src.n

    hits_before = cache.get_counters()["hits"]
    reg.register("prometheus", "samples", Src())
    query_range(store, "m", T0, T0 + 2, 1, **kw)
    query_range(store, "m", T0, T0 + 2, 1, **kw)
    # every pull is a new live generation → the token moves per query
    # and cached entries over moving live counters never serve stale
    assert cache.get_counters()["hits"] == hits_before


def test_result_cache_counters_dogfood_roundtrip():
    """Satellite pin: the cache registers as a Countable — its
    hit/miss/invalidation counters are queryable via SQL AND PromQL."""
    from deepflow_tpu.integration.dfstats import system_sink
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.utils.stats import StatsCollector

    cache = QueryResultCache(max_entries=8)
    cache.lookup(("q", "x", "db", "t"), token=0)   # one miss
    cache.store(("q", "x", "db", "t"), 0, [1])
    assert cache.lookup(("q", "x", "db", "t"), 0) == [1]  # one hit

    store = ColumnarStore()
    col = StatsCollector(interval_s=999)
    col.register("tpu_query_cache", cache)
    col.add_sink(system_sink(store))
    col.tick(now=float(T0))

    eng = QueryEngine(store, cache=False)
    for field, want in (("hits", 1.0), ("misses", 1.0), ("entries", 1.0)):
        res = eng.execute(
            "SELECT value FROM deepflow_system.deepflow_system "
            f"WHERE metric = 'tpu_query_cache_{field}'"
        )
        assert res.rows == 1 and float(res.values["value"][0]) == want, field
    out = query_instant(
        store, "tpu_query_cache_hits", T0 + 1,
        db=DEEPFLOW_SYSTEM_DB, table=DEEPFLOW_SYSTEM_TABLE,
    )
    assert len(out) == 1 and out[0]["value"] == 1.0


# ---------------------------------------------------------------------------
# SQL engine overlay + live-aware tier selection


def test_sql_engine_overlay_marks_partial_and_settles():
    from deepflow_tpu.querier.engine import QueryEngine

    store = ColumnarStore()
    ensure_system_table(store)
    reg = LiveRegistry()
    wm = WindowManager(WindowConfig(capacity=1 << 10, min_snapshot_interval=0.0))
    reg.register(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, PipelineLiveSource(wm))
    eng = QueryEngine(store, live=reg, cache=False)

    flushed = _doc_ingest(wm, T0, [10, 20], 100.0)
    sql = (
        "SELECT Sum(value) AS total FROM deepflow_system.deepflow_system "
        f"WHERE metric = '{LIVE_METRIC_FLOW_BYTES}'"
    )
    res = eng.execute(sql)
    assert res.partial is True
    assert float(res.values["total"][0]) == 200.0

    flushed += wm.flush_all()
    flow_window_sink(store)([f for f in flushed if f.count])
    res2 = eng.execute(sql)
    assert res2.partial is False  # snapshot now serves an empty span
    assert float(res2.values["total"][0]) == 200.0  # same answer, settled


def test_sql_overlay_no_double_count_from_stale_cached_snapshot():
    """Review regression (ISSUE 10): with a LARGE min_snapshot_interval
    the cached snapshot outlives a window close. The SQL engine has no
    per-series last-sample-wins dedup, so serving the stale partial
    alongside the window's flushed rows would DOUBLE-COUNT every
    aggregate. The provider must drop windows below the manager's
    CURRENT open span (a host int — no device read) at pull time."""
    from deepflow_tpu.querier.engine import QueryEngine

    store = ColumnarStore()
    ensure_system_table(store)
    reg = LiveRegistry()
    wm = WindowManager(
        WindowConfig(capacity=1 << 10, min_snapshot_interval=3600.0)
    )
    src = PipelineLiveSource(wm)
    reg.register(DEEPFLOW_SYSTEM_DB, DEEPFLOW_SYSTEM_TABLE, src)
    eng = QueryEngine(store, live=reg, cache=False)
    sql = (
        "SELECT Sum(value) AS total FROM deepflow_system.deepflow_system "
        f"WHERE metric = '{LIVE_METRIC_FLOW_BYTES}'"
    )

    _doc_ingest(wm, T0, [10, 20], 100.0)
    res = eng.execute(sql)
    assert res.partial and float(res.values["total"][0]) == 200.0
    # window T0 closes (advance) while the hour-long snapshot rate
    # limit keeps the pre-close snapshot cached; flushed rows land
    flushed = _doc_ingest(wm, T0 + 50, [99], 1.0)
    flow_window_sink(store)([f for f in flushed if f.count])
    res2 = eng.execute(sql)
    # 200 flushed + nothing from the stale partial (NOT 400); the new
    # open window at T0+50 is invisible until the next snapshot — a
    # freshness gap bounded by min_snapshot_interval, never a double
    assert float(res2.values["total"][0]) == 200.0
    assert not res2.partial
    # a fresh snapshot picks the new open window up again
    wm.snapshot_open(force=True)
    res3 = eng.execute(sql)
    assert res3.partial and float(res3.values["total"][0]) == 201.0


def test_tier_selection_prefers_live_covered_finest():
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.querier.translation import select_datasource_tier
    from deepflow_tpu.storage.store import ColumnSpec, TableSchema

    avail = {"network_1s": 1, "network_1m": 60}
    assert select_datasource_tier(avail, 60) == "network_1m"
    assert (
        select_datasource_tier(avail, 60, live_tables={"network_1s"})
        == "network_1s"
    )
    # a live tier that does NOT satisfy the step never wins
    assert (
        select_datasource_tier({"network_1m": 60}, 30, live_tables={"network_1m"})
        is None
    )

    store = ColumnarStore()
    for t in ("network_1s", "network_1m"):
        store.create_table("flow", TableSchema(t, (
            ColumnSpec("time", "u4"), ColumnSpec("byte_tx", "f8"),
        )))
    reg = LiveRegistry()
    eng = QueryEngine(store, live=reg, cache=False)
    # no live source: bare-name routing reads the coarsest fitting tier
    assert eng._resolve_table("network", step=60) == ("flow", "network_1m")

    class Src:
        def __call__(self, lo, hi):
            return None

        def open_from(self):
            return T0

    reg.register("flow", "network_1s", Src())
    # range touches the open span → the live-covered finest tier wins
    assert eng._resolve_table("network", step=60, trange=None) == (
        "flow", "network_1s"
    )
    assert eng._resolve_table("network", step=60, trange=(0, T0 + 10)) == (
        "flow", "network_1s"
    )
    # a bounded range entirely in the flushed past keeps the tier route
    assert eng._resolve_table("network", step=60, trange=(0, T0 - 100)) == (
        "flow", "network_1m"
    )


# ---------------------------------------------------------------------------
# feeder scheduling


def test_feeder_snapshot_scheduling_between_pumps():
    from deepflow_tpu.feeder import (
        FeederConfig,
        FeederRuntime,
        PipelineFeedSink,
        encode_flowbatch_frames,
    )
    from deepflow_tpu.ingest.queues import PyOverwriteQueue

    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 12, min_snapshot_interval=0.0),
        batch_size=256, bucket_sizes=(64, 128),
    ))
    q = PyOverwriteQueue(1 << 10)
    feeder = FeederRuntime(
        [q], PipelineFeedSink(pipe),
        FeederConfig(frames_per_queue=8, snapshot_interval_pumps=2),
    )
    gen = SyntheticFlowGen(num_tuples=100, seed=5)
    for i in range(4):
        for fr in encode_flowbatch_frames(
            gen.flow_batch(64, T0 + i), max_rows_per_frame=64
        ):
            q.put(fr)
        feeder.pump()
    c = feeder.get_counters()
    assert c["snapshots_taken"] == 2  # pumps 2 and 4
    assert c["snapshot_errors"] == 0
    assert feeder.last_snapshot is not None
    assert feeder.last_snapshot.windows  # open windows visible
    assert pipe.get_counters()["snapshot_reads"] >= 1
