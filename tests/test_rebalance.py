"""Elastic-topology unit coverage (ISSUE 15): topology overrides and
epochs, the rebalance protocol's edge cases (same-owner counted no-op,
single-flight guard, abort rollback), the ownership-transfer manifest
validation (stale pre-handover checkpoints refused with an error
naming both epochs), the receiver's epoch-flip hold buffer, and the
controller-side ShardGroupPlanner. Everything here is single-process:
standalone topologies for different process indices coexist in one
test process, so the cross-"host" restore matrix needs no subprocess.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from deepflow_tpu import chaos
from deepflow_tpu.aggregator.checkpoint import (
    restore_sharded_state,
    save_sharded_state,
)
from deepflow_tpu.chaos import RebalanceAbortError
from deepflow_tpu.controller.rebalance import ShardGroupPlanner
from deepflow_tpu.ops.histogram import LogHistSpec
from deepflow_tpu.parallel.rebalance import GroupRebalancer, plan_move
from deepflow_tpu.parallel.sharded import (
    ShardedConfig,
    ShardedPipeline,
    ShardedWindowManager,
)
from deepflow_tpu.parallel.topology import MeshTopology


def _cfg():
    return ShardedConfig(
        capacity_per_device=1 << 9, num_services=8, hll_precision=6,
        cms_depth=2, cms_width=128,
        hist=LogHistSpec(bins=16, vmin=1.0, gamma=1.5), topk_cols=32,
    )


def _swm(topology, group):
    return ShardedWindowManager(
        ShardedPipeline(topology, _cfg(), shard_group=group), delay=2
    )


def _feed(swm, t=1_700_000_000, n=64, seed=3):
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    fb = SyntheticFlowGen(num_tuples=32, seed=seed).flow_batch(n, t)
    return swm.ingest(fb.tags, fb.meters, fb.valid)


# ---------------------------------------------------------------------------
# topology overrides + epochs


def test_topology_rebalanced_overrides_and_epoch():
    t0 = MeshTopology.standalone(0, 2, n_groups=2, devices_per_group=1)
    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    assert t0.owned_groups() == (0,) and t1.owned_groups() == (1,)
    n0, n1 = t0.rebalanced(1, 0), t1.rebalanced(1, 0)
    # pure function: both hosts derive the identical placement + epoch
    assert n0.owned_groups() == (0, 1) and n1.owned_groups() == ()
    assert n0.topology_epoch == n1.topology_epoch == 1
    assert n0.group_process(1) == n1.group_process(1) == 0
    # moving a group back home drops the override but still bumps the
    # epoch (it IS a topology change)
    back = n0.rebalanced(1, 1)
    assert back.group_overrides == () and back.topology_epoch == 2
    assert back.owned_groups() == (0,)


def test_topology_adopted_group_mesh_uses_spare_devices():
    t0 = MeshTopology.standalone(0, 2, n_groups=2, devices_per_group=1)
    n0 = t0.rebalanced(1, 0)
    # the block group's devices never move under a flip; the adopted
    # group sits on the spare slice after the block range
    assert n0.group_mesh(0).devices.ravel().tolist() \
        == t0.group_mesh(0).devices.ravel().tolist()
    adopted = n0.group_mesh(1).devices.ravel().tolist()
    assert adopted and adopted != n0.group_mesh(0).devices.ravel().tolist()
    # a destination without spare local devices refuses loudly
    starved = MeshTopology.standalone(
        0, 2, n_groups=2, devices_per_group=1,
        devices=t0.local_devices[:1],
    )
    with pytest.raises(ValueError, match="local"):
        starved.rebalanced(1, 0)


def test_topology_later_adoption_never_rehomes_an_earlier_one():
    """Adopted slices follow ADOPTION order, not group number: a later
    adoption (even of a lower-numbered group) must not move a live
    adopted group's devices."""
    t0 = MeshTopology.standalone(0, 4, n_groups=4, devices_per_group=1)
    one = t0.rebalanced(3, 0)
    devs3 = one.group_mesh(3).devices.ravel().tolist()
    two = one.rebalanced(1, 0)
    assert two.group_mesh(3).devices.ravel().tolist() == devs3
    assert two.group_mesh(1).devices.ravel().tolist() != devs3
    assert two.owned_groups() == (0, 3, 1)


def test_topology_readoption_appends_as_newest_adoption():
    """A group that leaves and comes BACK must take the newest adopted
    slice: updating its override in place would resurrect its original
    position and silently re-home every adopted group that arrived
    after it left (two live managers sharing one device slice)."""
    t0 = MeshTopology.standalone(0, 4, n_groups=4, devices_per_group=1)
    two = t0.rebalanced(2, 0).rebalanced(3, 0)  # adoption order (2, 3)
    gone = two.rebalanced(2, 1)  # g2 leaves; g3 compacts to slice 0
    devs3 = gone.group_mesh(3).devices.ravel().tolist()
    back = gone.rebalanced(2, 0)  # g2 returns
    assert back.owned_groups() == (0, 3, 2)  # appended, not resurrected
    assert back.group_mesh(3).devices.ravel().tolist() == devs3
    assert back.group_mesh(2).devices.ravel().tolist() != devs3


def test_topology_describe_carries_epoch_and_owner():
    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    d = t1.describe()
    assert d["process_index"] == 1 and d["topology_epoch"] == 0
    assert t1.rebalanced(1, 0).describe()["topology_epoch"] == 1


# ---------------------------------------------------------------------------
# protocol edge cases


def test_rebalance_to_same_owner_is_counted_noop():
    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    reb = GroupRebalancer(t1, name="noop-test")
    assert plan_move(t1, 1, 1) is None
    assert reb.plan(1, 1) is None
    c = reb.get_counters()
    assert c["rebalance_noops"] == 1
    assert c["rebalances_planned"] == 0
    assert c["topology_epoch"] == 0  # nothing published


def test_concurrent_rebalance_same_group_fails_loudly():
    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    reb = GroupRebalancer(t1, name="flight-test")
    plan = reb.plan(1, 0)
    assert plan is not None
    with pytest.raises(RebalanceAbortError, match="single-flight"):
        reb.plan(1, 0)
    # aborting the first clears the guard
    reb.abort(plan)
    assert reb.plan(1, 0) is not None
    assert reb.get_counters()["rebalance_aborts"] == 1


def test_claim_fault_counts_abort_and_releases_guard():
    """A scripted fault at the claim step must not strand the group in
    the single-flight guard — the counted abort frees it so the
    controller can simply retry the plan."""
    from deepflow_tpu import chaos

    t0 = MeshTopology.standalone(0, 2, n_groups=2, devices_per_group=1)
    reb = GroupRebalancer(t0, name="claim-test")
    plan = reb.plan(1, 0)
    chaos.install(chaos.FaultPlan().add(chaos.FaultRule(
        site=chaos.SITE_REBALANCE_STEP, error=chaos.InjectedFault, at=(0,),
    )))
    try:
        with pytest.raises(RebalanceAbortError, match="claim of group 1"):
            reb.claim(plan)
    finally:
        chaos.uninstall()
    c = reb.get_counters()
    assert c["rebalance_aborts"] == 1 and c["inflight"] == 0
    # nothing moved; the retry plans and claims cleanly
    assert reb.topology.topology_epoch == 0
    plan2 = reb.plan(1, 0)
    assert reb.claim(plan2).topology_epoch == 1


def test_claim_failure_after_flip_rolls_back_so_retry_replans():
    """A claim that fails AFTER adopting the epoch (the receiver
    attach raising) must roll the topology back: otherwise the
    controller's documented retry plans against the half-flipped
    table, sees the move as already done (counted no-op), and the
    group strands with no handler anywhere."""
    t0 = MeshTopology.standalone(0, 2, n_groups=2, devices_per_group=1)
    reb = GroupRebalancer(t0, name="claim-rollback-test")

    class _BoomReceiver:
        routing = None
        calls = 0

        def attach_topology(self, topology, handoff=None):
            self.calls += 1
            if self.calls == 1:
                raise RuntimeError("boom")

    rx = _BoomReceiver()
    plan = reb.plan(1, 0)
    with pytest.raises(RebalanceAbortError, match="claim of group 1"):
        reb.claim(plan, receiver=rx)
    assert reb.topology.topology_epoch == 0  # rolled back
    assert rx.calls == 2  # the rollback re-attached the previous epoch
    plan2 = reb.plan(1, 0)  # the retry RE-PLANS — not a counted no-op
    assert plan2 is not None
    assert reb.claim(plan2, receiver=rx).topology_epoch == 1


def test_release_abort_restores_preexisting_handoff(tmp_path):
    """An aborted release must roll the receiver back to its PRE-FLIP
    handoff — rolling back to handoff=None would silently disable
    misroute forwarding for every group on the host after one aborted
    move of one group."""
    from deepflow_tpu.ingest.queues import PyOverwriteQueue
    from deepflow_tpu.ingest.receiver import Receiver

    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    rx = Receiver()

    def boot_handoff(group, raw):  # the fleet's bring-up forward
        return None

    rx.attach_topology(t1, boot_handoff)
    reb = GroupRebalancer(t1, name="handoff-rollback-test")
    swm = _swm(t1, 1)
    feeder = swm.make_feeder(
        [PyOverwriteQueue(64)], (64,), journal_dir=tmp_path
    )
    plan = reb.plan(1, 0)
    fault = chaos.FaultPlan().add(chaos.FaultRule(
        site=chaos.SITE_REBALANCE_STEP, error=chaos.TransientDeviceError,
        at=(1,),  # after the flip, before the quiesce
    ))
    with chaos.active(fault):
        with pytest.raises(RebalanceAbortError):
            reb.release(
                plan, feeder=feeder, save=lambda extra: None,
                receiver=rx, handoff=lambda group, raw: None,
            )
    topo, handoff, _ = rx.routing
    assert topo is t1
    assert handoff is boot_handoff  # restored, not None
    swm.close()


def test_release_abort_rolls_route_table_back(tmp_path):
    """An injected fault at the rebalance.step seam mid-release aborts
    LOUDLY and re-publishes the previous epoch — the group stays served
    by its old owner, the drain's outputs still reach the caller."""
    from deepflow_tpu.ingest.queues import PyOverwriteQueue

    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    reb = GroupRebalancer(t1, name="abort-test")
    swm = _swm(t1, 1)
    feeder = swm.make_feeder(
        [PyOverwriteQueue(64)], (64,), journal_dir=tmp_path
    )
    plan = reb.plan(1, 0)

    def save(extra):
        return save_sharded_state(swm, tmp_path / "h.ckpt", extra_meta=extra)

    fault = chaos.FaultPlan().add(chaos.FaultRule(
        site=chaos.SITE_REBALANCE_STEP, error=chaos.TransientDeviceError,
        at=(1,),  # after the flip, before the quiesce
    ))
    with chaos.active(fault):
        with pytest.raises(RebalanceAbortError):
            reb.release(plan, feeder=feeder, save=save)
    assert reb.topology is t1  # rolled back
    c = reb.get_counters()
    assert c["rebalance_aborts"] == 1 and c["inflight"] == 0
    # the aborted move leaves the group fully operable here
    plan2 = reb.plan(1, 0)
    assert plan2 is not None and plan2.epoch == 1


def test_quiesce_drains_large_fenced_backlog(tmp_path):
    """A FENCED backlog larger than any fixed pump allowance drains to
    completion: each pump moves a bounded frame budget, so quiesce must
    key its abort on backlog PROGRESS, not an iteration count — a big
    but fenced queue is a legitimate handover, not unfenced
    admission."""
    from deepflow_tpu.feeder import FeederConfig, encode_flowbatch_frames
    from deepflow_tpu.ingest.queues import PyOverwriteQueue
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    swm = _swm(t1, 1)
    q = PyOverwriteQueue(512)
    feeder = swm.make_feeder(
        [q], (64,),
        FeederConfig(frames_per_queue=1, rounds_per_pump=1),
        journal_dir=tmp_path,
    )
    gen = SyntheticFlowGen(num_tuples=16, seed=11)
    n_frames = 0
    for i in range(17):
        for fr in encode_flowbatch_frames(
            gen.flow_batch(16, 1_700_000_000 + i), agent_id=7,
            max_rows_per_frame=4,
        ):
            assert q.put(fr)
            n_frames += 1
    assert n_frames > 64  # a fixed 64-pump cap would spuriously abort
    feeder.quiesce(lambda meta: None)
    assert len(q) == 0
    assert feeder.get_counters()["records_in"] == 17 * 16
    swm.close()


def test_quiesce_unfenced_admission_aborts_loudly(tmp_path):
    """A queue whose backlog never shrinks across a pump (a producer
    still feeding — admission NOT fenced) aborts loudly instead of
    pumping forever or publishing incomplete state."""
    from deepflow_tpu.feeder import FeederConfig, encode_flowbatch_frames
    from deepflow_tpu.ingest.queues import PyOverwriteQueue  # noqa: F401
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    (frame,) = encode_flowbatch_frames(
        SyntheticFlowGen(num_tuples=8, seed=12).flow_batch(
            4, 1_700_000_000
        ),
        agent_id=7,
    )

    class _RefillingQueue:
        """Models unfenced admission: every drained frame is
        immediately replaced by the producer."""

        capacity = 0
        closed = False

        def __len__(self):
            return 4

        def gets(self, n, timeout_ms=0):
            return [frame] * max(1, min(n, 4))

    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    swm = _swm(t1, 1)
    feeder = swm.make_feeder(
        [_RefillingQueue()], (64,), FeederConfig(), journal_dir=tmp_path
    )
    with pytest.raises(RebalanceAbortError, match="not fenced"):
        feeder.quiesce(lambda meta: None)
    swm.close()


# ---------------------------------------------------------------------------
# ownership-transfer manifest validation at restore


def _handover_ckpt(tmp_path, *, manifest=True, epoch_delta=0):
    """Save group 1 under its old owner (standalone p1), optionally
    with a transfer manifest; return (path, new-owner topology)."""
    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    old = _swm(t1, 1)
    _feed(old)
    path = tmp_path / "hand.ckpt"
    extra = None
    if manifest:
        plan = plan_move(t1, 1, 0)
        extra = dict(plan.manifest_meta())
        if epoch_delta:
            extra["handover"] = dict(extra["handover"])
            extra["handover"]["topology_epoch"] += epoch_delta
    save_sharded_state(old, path, extra_meta=extra)
    old.close()
    t0_new = MeshTopology.standalone(
        0, 2, n_groups=2, devices_per_group=1
    ).rebalanced(1, 0)
    return path, t0_new


def test_stale_checkpoint_without_manifest_refused_naming_both_epochs(
        tmp_path):
    path, t0_new = _handover_ckpt(tmp_path, manifest=False)
    fresh = _swm(t0_new, 1)
    with pytest.raises(ValueError) as ei:
        restore_sharded_state(fresh, path)
    msg = str(ei.value)
    # both epochs named: the checkpoint's (0) and the restorer's (1)
    assert "epoch 0" in msg and "epoch 1" in msg
    assert "pre-handover" in msg
    fresh.close()


def test_manifest_with_wrong_epoch_refused_naming_both_epochs(tmp_path):
    path, t0_new = _handover_ckpt(tmp_path, epoch_delta=5)
    fresh = _swm(t0_new, 1)
    with pytest.raises(ValueError) as ei:
        restore_sharded_state(fresh, path)
    msg = str(ei.value)
    assert "epoch 6" in msg and "epoch 1" in msg
    fresh.close()


def test_old_owner_restoring_its_own_handover_checkpoint_refused(tmp_path):
    """The host that RELEASED a group must not restore the handover
    barrier it wrote — that would resurrect the group while its new
    owner serves it (split-brain over one key-hash range)."""
    path, _ = _handover_ckpt(tmp_path)
    t1 = MeshTopology.standalone(1, 2, n_groups=2, devices_per_group=1)
    back = _swm(t1, 1)
    with pytest.raises(ValueError, match="transferred group 1 to process 0"):
        restore_sharded_state(back, path)
    back.close()


def test_manifest_handover_restores_and_preserves_totals(tmp_path):
    path, t0_new = _handover_ckpt(tmp_path)
    fresh = _swm(t0_new, 1)
    restore_sharded_state(fresh, path)
    assert fresh.total_docs_in == 64  # counters continue across owners
    _feed(fresh, t=1_700_000_001, seed=4)
    assert fresh.total_docs_in == 128
    fresh.close()


def test_manifest_to_other_process_refused(tmp_path):
    t1 = MeshTopology.standalone(1, 3, n_groups=3, devices_per_group=1)
    old = _swm(t1, 1)
    _feed(old)
    path = tmp_path / "h.ckpt"
    save_sharded_state(
        old, path, extra_meta=plan_move(t1, 1, 2).manifest_meta()
    )
    old.close()
    hijacker = MeshTopology.standalone(
        0, 3, n_groups=3, devices_per_group=1
    ).rebalanced(1, 0)
    fresh = _swm(hijacker, 1)
    with pytest.raises(ValueError, match="to process 2"):
        restore_sharded_state(fresh, path)
    fresh.close()


# ---------------------------------------------------------------------------
# receiver epoch-flip hold buffer


def _frame(agent_id: int, org_id: int = 1) -> bytes:
    from deepflow_tpu.feeder import encode_flowbatch_frames
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    fb = SyntheticFlowGen(num_tuples=8, seed=9).flow_batch(4, 1_700_000_000)
    (raw,) = encode_flowbatch_frames(fb, agent_id=agent_id, org_id=org_id)
    return raw


def test_receiver_holds_and_redelivers_across_epoch_flip():
    from deepflow_tpu.ingest.framing import HEADER_LEN, FlowHeader, MessageType
    from deepflow_tpu.ingest.queues import PyOverwriteQueue
    from deepflow_tpu.ingest.receiver import Receiver
    from deepflow_tpu.parallel.topology import key_shard_group

    rx = Receiver(held_frames_cap=2)
    # an agent whose key-hash group is 1 of 2
    agent = next(
        a for a in range(64) if key_shard_group(1, a, 2) == 1
    )
    raw = _frame(agent)
    header = FlowHeader.parse(raw[:HEADER_LEN])
    topo = MeshTopology.standalone(0, 2, n_groups=2, devices_per_group=1)
    q0 = [PyOverwriteQueue(16)]
    rx.attach_topology(topo, handoff=None)
    rx.register_handler(MessageType.TAGGEDFLOW, q0, shard_group=0)
    # pre-flip: group 1 is remote — counted misroute (no handoff → drop)
    rx._dispatch(header, raw, ("t", 0))
    assert rx.counters["frames_misrouted"] == 1
    # flip: this process now owns group 1, but its handler is still
    # mid-restore — frames HOLD instead of misrouting
    rx.attach_topology(topo.rebalanced(1, 0), handoff=None)
    for _ in range(3):  # cap is 2: the third sheds the oldest, counted
        rx._dispatch(header, raw, ("t", 0))
    assert rx.counters["frames_held"] == 3
    assert rx.counters["frames_held_dropped"] == 1
    assert rx.counters["frames_misrouted"] == 1  # unchanged
    # registration redelivers the held frames, in order, into the queue
    q1 = [PyOverwriteQueue(16)]
    rx.register_handler(MessageType.TAGGEDFLOW, q1, shard_group=1)
    assert rx.counters["frames_redelivered"] == 2
    assert len(q1[0]) == 2 and len(q0[0]) == 0


def test_receiver_flip_away_forwards_previously_held_frames():
    """A held frame whose group flips AWAY on the next epoch leaves
    through the handoff, not the hold."""
    from deepflow_tpu.ingest.framing import HEADER_LEN, FlowHeader, MessageType
    from deepflow_tpu.ingest.queues import PyOverwriteQueue
    from deepflow_tpu.ingest.receiver import Receiver
    from deepflow_tpu.parallel.topology import key_shard_group

    rx = Receiver()
    agent = next(a for a in range(64) if key_shard_group(1, a, 2) == 1)
    raw = _frame(agent)
    header = FlowHeader.parse(raw[:HEADER_LEN])
    base = MeshTopology.standalone(0, 2, n_groups=2, devices_per_group=1)
    rx.register_handler(
        MessageType.TAGGEDFLOW, [PyOverwriteQueue(16)], shard_group=0
    )
    forwarded = []
    rx.attach_topology(base.rebalanced(1, 0), handoff=forwarded.append)
    rx._dispatch(header, raw, ("t", 0))
    assert rx.counters["frames_held"] == 1
    # the move reverses: group 1 goes home — the held frame must follow
    rx.attach_topology(
        base.rebalanced(1, 0).rebalanced(1, 1),
        handoff=lambda g, f: forwarded.append((g, len(f))),
    )
    assert rx.counters["frames_redelivered"] == 1
    assert rx.counters["frames_misrouted"] == 1
    assert forwarded == [(1, len(raw))]


# ---------------------------------------------------------------------------
# controller planning


def test_shard_group_planner_moves_dead_hosts_groups():
    pl = ShardGroupPlanner(dead_after_s=10)
    pl.heartbeat(0, [0], now=0.0)
    pl.heartbeat(1, [1, 2], now=0.0)
    pl.heartbeat(2, [3], now=0.0)
    assert pl.plan_moves(now=1.0) == []
    # host 1 dies: its two groups spread least-loaded-first
    pl.heartbeat(0, [0], now=20.0)
    pl.heartbeat(2, [3], now=20.0)
    moves = pl.plan_moves(now=21.0)
    assert moves == [(1, 0), (2, 2)]
    assert pl.counters["moves_planned"] == 2
    # level-triggered, not edge-triggered: until an owner claims them,
    # the same stranded groups keep being planned (a failed execution
    # loses only intent)...
    assert pl.plan_moves(now=21.0) == [(1, 0), (2, 2)]
    # ...and once live owners heartbeat them, the rescue is DONE — no
    # re-planning, no bouncing the group between hosts forever
    pl.heartbeat(0, [0, 1], now=22.0)
    pl.heartbeat(2, [3, 2], now=22.0)
    assert pl.plan_moves(now=23.0) == []
    # maintenance drain of a LIVE host empties it onto the others
    drains = pl.plan_drain(2, now=23.0)
    assert drains == [(2, 0), (3, 0)]


def test_shard_group_planner_dedupes_group_listed_by_two_dead_hosts():
    """Owner died, rescuer adopted, then the rescuer died before any
    planning tick pruned the first record: the group sits in TWO dead
    records and must still be planned exactly once — two adopters for
    one key range is the split-brain the manifest validation guards."""
    pl = ShardGroupPlanner(dead_after_s=10)
    pl.heartbeat(0, [0], now=0.0)
    pl.heartbeat(1, [5], now=0.0)     # original owner…
    pl.heartbeat(2, [5], now=5.0)     # …rescuer adopted 5, then
    pl.heartbeat(0, [0], now=30.0)    # both went silent
    moves = pl.plan_moves(now=31.0)
    assert moves == [(5, 0)]
    assert pl.counters["moves_planned"] == 1
