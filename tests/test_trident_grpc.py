"""trident.proto gRPC facade — a stock-agent-shaped client registers
over real gRPC, gets a stable vtap_id + config, and Push streams on
platform changes (reference: message/trident.proto Synchronizer)."""

from __future__ import annotations

import time

import pytest

grpc = pytest.importorskip("grpc")

from deepflow_tpu.controller.resources import ResourceDB
from deepflow_tpu.controller.trident_grpc import (
    TridentGrpcFacade,
    build_sync_response,
    parse_sync_request,
    parse_sync_response,
)
from deepflow_tpu.controller.trisolaris import TrisolarisService
from deepflow_tpu.ingest.codec import _put_varint


def _sync_request(ctrl_ip="10.0.0.9", ctrl_mac="aa:bb:cc:dd:ee:01",
                  group="", platform_version=0) -> bytes:
    out = bytearray()
    _put_varint(out, 1 << 3 | 0); _put_varint(out, 1_700_000_000)  # boot_time
    for field, s in ((5, "v6.4"), (7, "deepflow-agent"), (21, ctrl_ip),
                     (22, "host-1"), (25, ctrl_mac), (26, group)):
        b = s.encode()
        _put_varint(out, field << 3 | 2); _put_varint(out, len(b)); out += b
    _put_varint(out, 9 << 3 | 0); _put_varint(out, platform_version)
    _put_varint(out, 32 << 3 | 0); _put_varint(out, 4)  # cpu_num
    return bytes(out)


def test_wire_subset_roundtrip():
    req = parse_sync_request(_sync_request())
    assert req["ctrl_ip"] == "10.0.0.9" and req["ctrl_mac"] == "aa:bb:cc:dd:ee:01"
    assert req["process_name"] == "deepflow-agent" and req["cpu_num"] == 4
    resp = parse_sync_response(build_sync_response(
        vtap_id=7, sync_interval=30, platform_version=5, revision="v7"))
    assert resp["status"] == 0
    assert resp["config"] == {"enabled": True, "sync_interval": 30, "vtap_id": 7}
    assert resp["revision"] == "v7" and resp["version_platform_data"] == 5


@pytest.fixture()
def stack():
    db = ResourceDB()
    tri = TrisolarisService(db)
    facade = TridentGrpcFacade(tri, sync_interval=30, push_poll_s=0.05, push_heartbeat_s=0.3)
    chan = grpc.insecure_channel(f"127.0.0.1:{facade.port}")
    yield db, tri, facade, chan
    chan.close()
    facade.stop()
    tri.stop()


def _stub(chan, method):
    return chan.unary_unary(
        f"/trident.Synchronizer/{method}",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )


def test_stock_agent_registers_and_keeps_vtap_id(stack):
    db, tri, facade, chan = stack
    sync = _stub(chan, "Sync")
    r1 = parse_sync_response(sync(_sync_request()))
    assert r1["status"] == 0
    vid = r1["config"]["vtap_id"]
    assert vid >= 1 and r1["config"]["enabled"]

    # same identity → same id; new MAC → new id (IP_AND_MAC identity)
    r2 = parse_sync_response(sync(_sync_request()))
    assert r2["config"]["vtap_id"] == vid
    r3 = parse_sync_response(sync(_sync_request(ctrl_mac="aa:bb:cc:dd:ee:02")))
    assert r3["config"]["vtap_id"] != vid
    assert facade.counters["registers"] == 2

    # the agent shows up in trisolaris' agent table under its vtap_id
    assert vid in tri.agents

    # AnalyzerSync rides the same handler
    r4 = parse_sync_response(_stub(chan, "AnalyzerSync")(_sync_request()))
    assert r4["config"]["vtap_id"] == vid


def test_group_request_routes_group_config(stack):
    db, tri, facade, chan = stack
    tri.set_group_config("edge", {"l4_log_collect_nps_threshold": 777})
    sync = _stub(chan, "Sync")
    r = parse_sync_response(sync(_sync_request(group="edge")))
    vid = r["config"]["vtap_id"]
    assert tri.agents[vid]["group"] == "edge"


def test_push_streams_on_platform_change(stack):
    db, tri, facade, chan = stack
    push = chan.unary_stream(
        "/trident.Synchronizer/Push",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    stream = push(_sync_request())
    first = parse_sync_response(next(stream))
    assert first["status"] == 0
    v0 = first["version_platform_data"]
    # a platform change (new resource) reaches the agent through the
    # stream — possibly on a heartbeat frame that raced the change
    # detector, so scan a few frames rather than pinning which one
    db.put("pod", 9001, "web-9001")
    nxt = first
    for _ in range(10):
        nxt = parse_sync_response(next(stream))
        if nxt["version_platform_data"] > v0:
            break
    assert nxt["version_platform_data"] > v0
    stream.cancel()
