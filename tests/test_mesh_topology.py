"""Multi-host mesh topology + key-hash fan-in units (ISSUE 14).

In-process coverage of the placement layer: key-hash group assignment,
MeshTopology ownership math, per-host path naming, receiver routing
(misroute counting + control-plane handoff, queryable in
deepflow_system), checkpoint topology validation, and the per-group
freshness/lineage labels. The REAL 2-process deployment is covered by
tests/test_mesh_multiproc.py over the mesh_harness subprocess run.
"""

from __future__ import annotations

import numpy as np
import pytest

from deepflow_tpu.ingest.framing import HEADER_LEN, FlowHeader, MessageType
from deepflow_tpu.ingest.queues import PyOverwriteQueue
from deepflow_tpu.ingest.receiver import Receiver
from deepflow_tpu.parallel.topology import (
    MeshTopology,
    key_shard_group,
)

T0 = 1_700_000_000


# ---------------------------------------------------------------------------
# key-hash fan-in


def test_key_shard_group_deterministic_and_vectorized():
    a = key_shard_group(1, 5, 4)
    assert a == key_shard_group(1, 5, 4)  # pure function
    assert 0 <= a < 4
    orgs = np.full(64, 1, np.uint32)
    agents = np.arange(64, dtype=np.uint32)
    vec = key_shard_group(orgs, agents, 4)
    assert vec.shape == (64,)
    # vector path == scalar path, element for element
    for i in (0, 3, 17, 63):
        assert int(vec[i]) == key_shard_group(1, i, 4)
    # the hash actually spreads agents over every group
    assert set(vec.tolist()) == {0, 1, 2, 3}
    # org participates in the key words (different org can move agents)
    vec2 = key_shard_group(np.full(64, 7, np.uint32), agents, 4)
    assert vec2.tolist() != vec.tolist()


def test_key_shard_group_rejects_bad_group_count():
    with pytest.raises(ValueError):
        key_shard_group(1, 2, 0)


# ---------------------------------------------------------------------------
# placement math


def test_single_topology_owns_everything_with_disjoint_group_meshes():
    t = MeshTopology.single(n_groups=4, devices_per_group=2)
    assert t.owned_groups() == (0, 1, 2, 3)
    seen = set()
    for g in range(4):
        mesh = t.group_mesh(g)
        # the data-path contract: same axis names as the single-process
        # mesh, so shard_map bodies are untouched
        assert mesh.axis_names == ("host", "chip")
        assert mesh.devices.size == 2
        devs = {d.id for d in mesh.devices.ravel()}
        assert not (devs & seen), "group meshes must not share devices"
        seen |= devs
    gm = t.global_mesh()
    assert gm.axis_names == ("host", "chip")


def test_standalone_topology_is_coordination_free_but_loud():
    t = MeshTopology.standalone(1, 2, devices_per_group=1)
    assert t.owned_groups() == (1,)
    assert t.group_mesh(1).devices.size == 1
    # a remote group's mesh must never be constructible — the data
    # path never crosses hosts
    with pytest.raises(ValueError, match="never crosses hosts"):
        t.group_mesh(0)
    with pytest.raises(ValueError, match="no global device view"):
        t.global_mesh()


def test_topology_validation_is_loud():
    with pytest.raises(ValueError, match="divide evenly"):
        MeshTopology.standalone(0, 3, n_groups=4)
    with pytest.raises(ValueError, match="outside"):
        MeshTopology.standalone(5, 2)
    with pytest.raises(ValueError, match="only .* are local"):
        MeshTopology.single(n_groups=1, devices_per_group=1024)


def test_host_path_carries_process_and_group():
    t = MeshTopology.standalone(1, 4, n_groups=4, devices_per_group=1)
    p = t.host_path("/var/lib/deepflow/feeder.journal", group=1)
    assert p.name == "feeder.journal.g1.p1of4"
    q = t.host_path("/var/lib/deepflow/mesh.ckpt")
    assert q.name == "mesh.ckpt.p1of4"


# ---------------------------------------------------------------------------
# receiver key-hash routing


def _frames_for_agents(n_agents: int, rows: int = 16):
    from deepflow_tpu.feeder import encode_flowbatch_frames
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    gen = SyntheticFlowGen(num_tuples=32, seed=3)
    out = []
    for a in range(n_agents):
        fb = gen.flow_batch(rows, T0)
        out += [
            (a, raw)
            for raw in encode_flowbatch_frames(fb, agent_id=a, org_id=1)
        ]
    return out


def test_receiver_routes_by_key_hash_and_counts_misroutes():
    topo = MeshTopology.standalone(0, 2, devices_per_group=1)
    rx = Receiver()
    handed = []
    rx.attach_topology(topo, handoff=lambda g, raw: handed.append(g))
    q_own = PyOverwriteQueue(256)
    rx.register_handler(MessageType.TAGGEDFLOW, [q_own], shard_group=0)
    # a wrong-group handler that must NEVER see a frame
    q_other = PyOverwriteQueue(256)
    rx.register_handler(MessageType.TAGGEDFLOW, [q_other], shard_group=1)

    frames = _frames_for_agents(12)
    own = misrouted = 0
    for agent, raw in frames:
        g = topo.group_for_agent(1, agent)
        if topo.owns_group(g):
            own += 1
        else:
            misrouted += 1
        rx._dispatch(FlowHeader.parse(raw[:HEADER_LEN]), raw, ("test", 0))
    assert own > 0 and misrouted > 0  # the hash split this agent set
    c = rx.get_counters()
    assert len(q_own) == own
    # the misrouted frames were counted and handed off — NOT enqueued
    # into the wrong-group handler registered on this same receiver
    assert len(q_other) == 0
    assert c["frames_misrouted"] == misrouted
    assert c["frames_handoff"] == misrouted
    assert handed and all(not topo.owns_group(g) for g in handed)
    rx.stop()


def test_receiver_handoff_errors_are_contained_and_counted():
    topo = MeshTopology.standalone(0, 2, devices_per_group=1)
    rx = Receiver()

    def broken(_g, _raw):
        raise RuntimeError("control-plane link down")

    rx.attach_topology(topo, handoff=broken)
    rx.register_handler(
        MessageType.TAGGEDFLOW, [PyOverwriteQueue(64)], shard_group=0
    )
    for agent, raw in _frames_for_agents(12):
        rx._dispatch(FlowHeader.parse(raw[:HEADER_LEN]), raw, ("test", 0))
    c = rx.get_counters()
    assert c["frames_misrouted"] > 0
    assert c["handoff_errors"] == c["frames_misrouted"]
    assert c["frames_handoff"] == 0
    rx.stop()


def test_receiver_misroute_counter_queryable_in_deepflow_system():
    from deepflow_tpu.integration.dfstats import (
        system_metric_name,
        system_sink,
    )
    from deepflow_tpu.querier.engine import QueryEngine
    from deepflow_tpu.storage.store import ColumnarStore
    from deepflow_tpu.utils.stats import StatsCollector

    topo = MeshTopology.standalone(0, 2, devices_per_group=1)
    rx = Receiver()
    rx.attach_topology(topo)  # no handoff: counted drops
    rx.register_handler(
        MessageType.TAGGEDFLOW, [PyOverwriteQueue(256)], shard_group=0
    )
    for agent, raw in _frames_for_agents(12):
        rx._dispatch(FlowHeader.parse(raw[:HEADER_LEN]), raw, ("test", 0))
    want = rx.get_counters()["frames_misrouted"]
    assert want > 0

    store = ColumnarStore()
    col = StatsCollector(interval_s=999)
    col.register("tpu_receiver", rx)
    col.add_sink(system_sink(store))
    col.tick(now=float(T0 + 100))
    res = QueryEngine(store).execute(
        "SELECT value FROM deepflow_system.deepflow_system WHERE metric = "
        f"'{system_metric_name('tpu_receiver', 'frames_misrouted')}'"
    )
    assert res.rows == 1
    assert float(res.values["value"][0]) == float(want)
    rx.stop()


def test_ungrouped_lanes_bypass_routing_even_with_topology_attached():
    """Review regression: routing applies ONLY to message types with
    group-registered handlers. A receiver serving the sharded
    TAGGEDFLOW plane AND an ungrouped lane (METRICS/SYSLOG-style) must
    keep delivering the ungrouped lane's frames from EVERY agent —
    gating them behind the key-hash would drop half the fleet's
    metrics the moment a topology attaches."""
    from deepflow_tpu.ingest.framing import FlowHeader, MessageType

    topo = MeshTopology.standalone(0, 2, devices_per_group=1)
    rx = Receiver()
    rx.attach_topology(topo)
    q_flow = PyOverwriteQueue(256)
    rx.register_handler(MessageType.TAGGEDFLOW, [q_flow], shard_group=0)
    q_metrics = PyOverwriteQueue(256)
    rx.register_handler(MessageType.METRICS, [q_metrics])  # ungrouped

    frames = _frames_for_agents(12)
    n_own = sum(
        1 for a, _ in frames if topo.owns_group(topo.group_for_agent(1, a))
    )
    for _agent, raw in frames:
        rx._dispatch(FlowHeader.parse(raw[:HEADER_LEN]), raw, ("t", 0))
        # the same agent's frame re-framed onto the ungrouped lane
        header = FlowHeader.parse(raw[:HEADER_LEN])
        header.msg_type = int(MessageType.METRICS)
        m_raw = header.encode() + raw[HEADER_LEN:]
        rx._dispatch(FlowHeader.parse(m_raw[:HEADER_LEN]), m_raw, ("t", 0))
    # grouped lane routed; ungrouped lane delivered EVERYTHING
    assert len(q_flow) == n_own
    assert len(q_metrics) == len(frames)
    # misroutes counted only for the grouped lane
    assert rx.get_counters()["frames_misrouted"] == len(frames) - n_own
    rx.stop()


def test_reattach_invalidates_cached_agent_groups():
    """Review regression: the (topology, handoff, epoch) tuple is
    published atomically — after a re-attach with a different group
    count, every agent's cached group is recomputed under the NEW
    topology (a stale group could land in a wrong-group handler or
    fall outside the new range)."""
    from deepflow_tpu.ingest.framing import FlowHeader, MessageType

    rx = Receiver()
    frames = _frames_for_agents(8)
    t2 = MeshTopology.single(n_groups=2, devices_per_group=1)
    rx.attach_topology(t2)
    q = {g: PyOverwriteQueue(256) for g in range(4)}
    for g in range(2):
        rx.register_handler(MessageType.TAGGEDFLOW, [q[g]], shard_group=g)
    for _a, raw in frames:
        rx._dispatch(FlowHeader.parse(raw[:HEADER_LEN]), raw, ("t", 0))
    t4 = MeshTopology.single(n_groups=4, devices_per_group=1)
    rx.attach_topology(t4)
    for g in range(2, 4):
        rx.register_handler(MessageType.TAGGEDFLOW, [q[g]], shard_group=g)
    for a, raw in frames:
        rx._dispatch(FlowHeader.parse(raw[:HEADER_LEN]), raw, ("t", 0))
    # second pass routed under the 4-group map, not the cached 2-group
    # (the cache is one atomic (epoch, group) tuple)
    for a in {a for a, _ in frames}:
        epoch, group = rx.agents[(1, a)].route
        assert group == t4.group_for_agent(1, a)
        assert epoch == rx._route_epoch
    assert rx.get_counters()["frames_misrouted"] == 0  # all groups local
    rx.stop()


def test_ungrouped_handler_still_works_without_topology():
    rx = Receiver()
    q = PyOverwriteQueue(64)
    rx.register_handler(MessageType.TAGGEDFLOW, [q])
    _, raw = _frames_for_agents(1)[0]
    rx._dispatch(FlowHeader.parse(raw[:HEADER_LEN]), raw, ("test", 0))
    assert len(q) == 1
    assert rx.get_counters()["frames_misrouted"] == 0
    rx.stop()


# ---------------------------------------------------------------------------
# pipeline threading + per-host journal naming


def _mk_swm(topology, group):
    from deepflow_tpu.parallel.sharded import (
        ShardedPipeline,
        ShardedWindowManager,
    )

    # the mesh_harness config: identical shapes → the sharded kernels
    # compiled by the multiproc oracle (earlier in the suite) are jit
    # cache hits here
    from mesh_harness import _sharded_cfg

    return ShardedWindowManager(
        ShardedPipeline(topology, _sharded_cfg(), shard_group=group), delay=2
    )


def test_sharded_pipeline_from_topology_keeps_axes_and_journals_per_host(
    tmp_path,
):
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    topo = MeshTopology.single(n_groups=2, devices_per_group=1)
    wm = _mk_swm(topo, 1)
    assert wm.pipe.axes == ("host", "chip")
    assert wm.pipe.n_devices == 1
    assert wm.pipe.topology is topo and wm.pipe.shard_group == 1
    feeder = wm.make_feeder(
        [PyOverwriteQueue(64)], (64, 128), journal_dir=tmp_path
    )
    jpath = tmp_path / "feeder.journal.g1.p0of1"
    assert jpath.exists(), "journal filename must carry group + process"
    gen = SyntheticFlowGen(num_tuples=16, seed=5)
    fb = gen.flow_batch(64, T0)
    wm.ingest(fb.tags, fb.meters, fb.valid)
    fb2 = gen.flow_batch(64, T0 + 8)
    assert wm.ingest(fb2.tags, fb2.meters, fb2.valid)  # windows closed
    feeder._journal.close()
    wm.close()


def test_remote_group_pipeline_refused_at_construction():
    from deepflow_tpu.parallel.sharded import ShardedPipeline

    topo = MeshTopology.standalone(0, 2, devices_per_group=1)
    with pytest.raises(ValueError, match="never crosses hosts"):
        ShardedPipeline(topo, shard_group=1)


# ---------------------------------------------------------------------------
# checkpoint topology validation (satellite: loud at load, not a shape
# error deep in shard_map)


def _ingest_one(wm):
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    gen = SyntheticFlowGen(num_tuples=16, seed=9)
    fb = gen.flow_batch(32, T0)
    wm.ingest(fb.tags, fb.meters, fb.valid)
    return wm


def test_sharded_checkpoint_validates_mesh_topology_loudly(tmp_path):
    from deepflow_tpu.aggregator.checkpoint import (
        read_checkpoint_meta,
        restore_sharded_state,
        save_sharded_state,
    )

    topo = MeshTopology.single(n_groups=2, devices_per_group=1)
    wm = _ingest_one(_mk_swm(topo, 0))
    path = tmp_path / "g0.ckpt"
    save_sharded_state(wm, path)
    meta = read_checkpoint_meta(path)
    assert meta["process_count"] == 1 and meta["n_groups"] == 2
    assert meta["shard_group"] == 0

    # same topology, same group → restores
    fresh = _mk_swm(MeshTopology.single(n_groups=2, devices_per_group=1), 0)
    restore_sharded_state(fresh, path)
    assert fresh.start_window == wm.start_window

    # a different process count is a different mesh shape → loud
    bad_topo = MeshTopology.standalone(0, 2, devices_per_group=1)
    with pytest.raises(ValueError, match="mesh topology"):
        restore_sharded_state(_mk_swm(bad_topo, 0), path)

    # the right topology but the WRONG shard group → loud (the restore
    # would silently serve another group's key-hash range)
    with pytest.raises(ValueError, match="key-hash range"):
        restore_sharded_state(
            _mk_swm(MeshTopology.single(n_groups=2, devices_per_group=1), 1),
            path,
        )


def test_multiproc_checkpoint_refuses_topologyless_restore(tmp_path):
    from deepflow_tpu.aggregator import checkpoint as ckpt_mod
    from deepflow_tpu.aggregator.checkpoint import (
        restore_sharded_state,
        save_sharded_state,
    )
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import ShardedPipeline, ShardedWindowManager

    topo = MeshTopology.single(n_groups=2, devices_per_group=1)
    wm = _ingest_one(_mk_swm(topo, 0))
    path = tmp_path / "g0.ckpt"
    save_sharded_state(wm, path)

    bare = ShardedWindowManager(
        ShardedPipeline(make_mesh(1), wm.pipe.config), delay=2
    )
    # review regression: even a SINGLE-process save is one shard
    # group's slice when n_groups > 1 — a bare manager restoring it
    # would serve the full key range with only that group's stashes
    with pytest.raises(ValueError, match="topology-less"):
        restore_sharded_state(bare, path)

    # forge a 2-process save (the single-process harness cannot produce
    # one in-process; the meta contract is what matters here)
    meta, arrays = ckpt_mod._read_checkpoint(path)
    meta.pop("digest", None)
    meta["process_count"] = 2
    ckpt_mod._write_checkpoint(path, meta, arrays)
    with pytest.raises(ValueError, match="topology-less"):
        restore_sharded_state(bare, path)


# ---------------------------------------------------------------------------
# per-shard-group freshness lanes + cross-host trace identity


def test_freshness_lanes_carry_group_label():
    from deepflow_tpu.tracing.lineage import FreshnessTracker
    from deepflow_tpu.utils.stats import StatsCollector

    col = StatsCollector(interval_s=999)
    ft = FreshnessTracker(name="gtest", group="3", collector=col)
    ft.observe("flush", 1, 0.5, T0, "tid")
    srcs = [s for s in col._sources if s.module == "tpu_freshness"]
    assert srcs
    tags = dict(srcs[0].tags)
    assert tags.get("group") == "3"
    assert tags.get("tier") == "1s"
    ft.close()


def test_trace_ids_are_host_invariant_but_lanes_are_per_group():
    """One trace per window ACROSS hosts: the id is a pure function of
    (service, window, interval) — two hosts' trackers for different
    shard groups join the same trace with zero wire context."""
    from deepflow_tpu.tracing.lineage import LineageTracker

    a = LineageTracker(service="podsvc", interval=1, group="0")
    b = LineageTracker(service="podsvc", interval=1, group="1")
    try:
        assert a.trace_id_of(12345) == b.trace_id_of(12345)
        assert a.group == "0" and b.group == "1"
    finally:
        a.close()
        b.close()
