"""Feeder runtime (ISSUE 4): K-batch counter ring bit-exactness,
multi-queue fan-in + shape-bucketed coalescing, deterministic shedding,
queue/receiver satellites, checkpoint v1 removal."""

from __future__ import annotations

import numpy as np
import pytest

from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
from deepflow_tpu.aggregator.window import WindowConfig, WindowManager
from deepflow_tpu.datamodel.batch import FlowBatch
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.feeder import (
    FeederConfig,
    FeederRuntime,
    PipelineFeedSink,
    WindowManagerFeedSink,
    decode_flowframe_body,
    encode_flowbatch_body,
    encode_flowbatch_frames,
    peek_rows,
)
from deepflow_tpu.ingest.queues import PyOverwriteQueue, register_queue_stats
from deepflow_tpu.ingest.replay import SyntheticFlowGen

T0 = 1_700_000_000


def _doc_key(db):
    return (db.size, float(db.meters.sum()), int(db.tags.sum()),
            int(db.timestamp.sum()))


def _run_pipeline(K, sizes, *, buckets=None, seed=3, async_drain=False):
    cfg = PipelineConfig(
        window=WindowConfig(capacity=1 << 12, stats_ring=K,
                            async_drain=async_drain),
        batch_size=256,
        bucket_sizes=buckets,
    )
    gen = SyntheticFlowGen(num_tuples=200, seed=seed)
    pipe = L4Pipeline(cfg)
    docs = []
    for i, n in enumerate(sizes):
        docs += pipe.ingest(FlowBatch.from_records(gen.records(n, T0 + i)))
    docs += pipe.drain()
    return sorted(_doc_key(db) for db in docs), pipe.get_counters()


# ---------------------------------------------------------------------------
# K-batch counter ring


@pytest.mark.parametrize("K", [4, 7])
def test_stats_ring_bit_exact_vs_per_batch_oracle(K):
    """K ∈ {4, 7} with one window advance per batch — every advance
    lands mid-ring (12 batches is not a multiple of 7, and the drain
    points never align with the closes). Flushed windows must be
    bit-exact vs the per-batch fetch oracle (K=1)."""
    sizes = [64] * 12
    oracle, c1 = _run_pipeline(1, sizes)
    ringed, cK = _run_pipeline(K, sizes)
    assert ringed == oracle
    # same funnel accounting once settled
    for key in ("doc_in", "flushed_doc", "drop_before_window",
                "window_advances"):
        assert cK[key] == c1[key], key
    # and strictly fewer stats fetches: 1 per K batches instead of 1/batch
    assert cK["host_fetches"] < c1["host_fetches"]


def test_stats_ring_late_rows_gated_identically():
    """Out-of-order traffic where the deferred gate matters: batches
    jump forward (closing windows mid-ring) then fall back inside and
    beyond the delay. The device-resident start_window must drop
    exactly what per-batch fetching would have dropped."""
    def run(K):
        cfg = PipelineConfig(
            window=WindowConfig(capacity=1 << 12, stats_ring=K),
            batch_size=64,
        )
        gen = SyntheticFlowGen(num_tuples=50, seed=9)
        pipe = L4Pipeline(cfg)
        docs = []
        # t pattern: advance to T0+10 closes windows; T0+1 is then LATE
        # (before start_window), T0+9 is within delay
        for t in (T0, T0 + 1, T0 + 2, T0 + 10, T0 + 1, T0 + 9, T0 + 11,
                  T0 + 3, T0 + 12, T0 + 30, T0 + 5, T0 + 31):
            docs += pipe.ingest(FlowBatch.from_records(gen.records(32, t)))
        docs += pipe.drain()
        return sorted(_doc_key(db) for db in docs), pipe.get_counters()

    oracle, c1 = run(1)
    assert c1["drop_before_window"] > 0  # the scenario exercises the gate
    for K in (4, 7):
        ringed, cK = run(K)
        assert ringed == oracle, K
        assert cK["drop_before_window"] == c1["drop_before_window"]


def test_stats_ring_settle_on_partial_ring():
    """drain-on-checkpoint: settle() fetches a partially-filled ring so
    host counters catch up without waiting for K dispatches."""
    cfg = PipelineConfig(
        window=WindowConfig(capacity=1 << 12, stats_ring=8), batch_size=64
    )
    gen = SyntheticFlowGen(num_tuples=50, seed=4)
    pipe = L4Pipeline(cfg)
    for i in range(3):  # 3 < K=8: nothing fetched yet
        pipe.ingest(FlowBatch.from_records(gen.records(40, T0 + i)))
    c = pipe.get_counters()
    assert c["doc_in"] == 0 and c["stats_ring_pending"] == 3
    pipe.wm.settle()
    c = pipe.get_counters()
    assert c["stats_ring_pending"] == 0
    assert c["doc_in"] > 0  # blocks replayed into host counters


def test_stats_ring_checkpoint_roundtrip(tmp_path):
    """Mid-stream save/restore with a filled ring: nothing lost or
    duplicated (save settles the ring first)."""
    from deepflow_tpu.aggregator.checkpoint import (
        load_window_state,
        save_window_state,
    )

    cfg = PipelineConfig(
        window=WindowConfig(capacity=1 << 12, stats_ring=4), batch_size=64
    )
    stream = [(T0, 40), (T0 + 1, 40), (T0 + 10, 40), (T0 + 11, 30)]

    def run(save_after):
        gen = SyntheticFlowGen(num_tuples=40, seed=7)
        pipe = L4Pipeline(cfg)
        docs = []
        for i, (t, n) in enumerate(stream):
            docs += pipe.ingest(FlowBatch.from_records(gen.records(n, t)))
            if save_after == i:
                in_flight = save_window_state(pipe.wm, tmp_path / "wm.ckpt")
                docs += [pipe._to_docbatch(f) for f in in_flight]
                pipe = L4Pipeline(cfg)
                pipe.wm = load_window_state(
                    tmp_path / "wm.ckpt", TAG_SCHEMA, FLOW_METER
                )
        docs += pipe.drain()
        c = FLOW_METER.index("packet_tx")
        return (sum(float(db.meters[:, c].sum()) for db in docs),
                sum(db.size for db in docs))

    assert run(save_after=1) == run(save_after=None)


def test_stats_ring_opening_batch_spanning_delay():
    """Regression (r9 review): when the FIRST non-empty batch spans
    more than `delay` seconds, the host opens the span AND advances it
    within the same block — the device gate must land on the advanced
    value, or ring mode admits rows per-batch mode late-drops."""
    def run(K):
        cfg = PipelineConfig(
            window=WindowConfig(interval=1, delay=0, capacity=1 << 10,
                                stats_ring=K),
            batch_size=64,
        )
        gen = SyntheticFlowGen(num_tuples=20, seed=13)
        pipe = L4Pipeline(cfg)
        docs = []
        # batch 1 spans [T0, T0+5] (> delay=0); batch 2's T0+2 rows are
        # late in per-batch mode and must be late in ring mode too
        r1 = gen.records(8, T0)
        r1 += gen.records(8, T0 + 5)
        docs += pipe.ingest(FlowBatch.from_records(r1))
        docs += pipe.ingest(FlowBatch.from_records(gen.records(8, T0 + 2)))
        docs += pipe.drain()
        return sorted(_doc_key(db) for db in docs), pipe.get_counters()

    oracle, c1 = run(1)
    assert c1["drop_before_window"] > 0  # the scenario exercises the race
    ringed, c4 = run(4)
    assert ringed == oracle
    assert c4["drop_before_window"] == c1["drop_before_window"]


def test_stats_ring_flush_all_resyncs_device_gate():
    """Regression (r9 review): flush_all() jumps the host span past
    every drained window; the device gate must follow, or a straggler
    ingest re-opens an already-emitted window and it flushes TWICE."""
    def run(K):
        cfg = PipelineConfig(
            window=WindowConfig(capacity=1 << 10, stats_ring=K),
            batch_size=64,
        )
        gen = SyntheticFlowGen(num_tuples=20, seed=17)
        pipe = L4Pipeline(cfg)
        docs = []
        docs += pipe.ingest(FlowBatch.from_records(gen.records(16, T0)))
        docs += pipe.drain()  # emits window T0; span moves past it
        # straggler at T0 again: must be late-dropped on BOTH paths
        docs += pipe.ingest(FlowBatch.from_records(gen.records(16, T0)))
        docs += pipe.drain()
        return sorted(_doc_key(db) for db in docs), pipe.get_counters()

    oracle, c1 = run(1)
    ringed, c4 = run(4)
    assert ringed == oracle
    assert c4["drop_before_window"] == c1["drop_before_window"] > 0
    assert c4["flushed_doc"] == c1["flushed_doc"]


def test_stats_ring_rejects_async_drain_combo():
    with pytest.raises(ValueError, match="stats_ring"):
        WindowManager(WindowConfig(stats_ring=4, async_drain=True))


# ---------------------------------------------------------------------------
# shape buckets


def test_bucketed_ingest_zero_retraces_and_bit_exact():
    sizes = [30, 64, 100, 256, 17, 200, 64, 90, 256, 11]
    oracle, _ = _run_pipeline(1, sizes, buckets=(64, 128, 256))
    got, c = _run_pipeline(4, sizes, buckets=(64, 128, 256))
    assert got == oracle
    assert c["jit_retraces"] == 0
    assert 1 <= c["jit_compiles"] <= 3  # ≤ one compile per bucket
    over, _ = _run_pipeline(1, [10], buckets=(64, 128, 256))  # fits fine
    with pytest.raises(ValueError, match="bucket"):
        _run_pipeline(1, [300], buckets=(64, 128, 256))


def test_bucket_sizes_validated():
    with pytest.raises(ValueError, match="bucket_sizes"):
        PipelineConfig(bucket_sizes=(128, 64))
    with pytest.raises(ValueError, match="bucket_sizes"):
        PipelineConfig(bucket_sizes=())


def test_jit_cache_monitor_expected_compiles():
    from deepflow_tpu.utils.spans import JitCacheMonitor

    class FakeFn:
        size = 0

        def _cache_size(self):
            return self.size

    fn = FakeFn()
    mon = JitCacheMonitor(fn, expected_compiles=3)
    fn.size = 2
    mon.poll()
    assert (mon.compiles, mon.retraces) == (2, 0)
    fn.size = 3
    mon.poll()
    assert (mon.compiles, mon.retraces) == (3, 0)
    fn.size = 5  # beyond the bucket budget → real retraces
    mon.poll()
    assert (mon.compiles, mon.retraces) == (3, 2)


# ---------------------------------------------------------------------------
# flowframe codec


def test_flowframe_roundtrip_and_peek():
    gen = SyntheticFlowGen(num_tuples=30, seed=1)
    fb = gen.flow_batch(50, T0)
    fb.valid[40:] = False  # only valid rows travel
    body = encode_flowbatch_body(fb)
    assert peek_rows(body) == 40
    out = decode_flowframe_body(body)
    assert out.size == 40 and bool(out.valid.all())
    for k in fb.tags:
        np.testing.assert_array_equal(out.tags[k], fb.tags[k][:40])
    np.testing.assert_array_equal(out.meters, fb.meters[:40])


def test_flowframe_rejects_garbage():
    with pytest.raises(ValueError, match="magic"):
        decode_flowframe_body(b"\x00" * 64)
    gen = SyntheticFlowGen(num_tuples=10, seed=1)
    body = encode_flowbatch_body(gen.flow_batch(8, T0))
    with pytest.raises(ValueError, match="truncated"):
        decode_flowframe_body(body[:-8])
    assert peek_rows(b"\x00" * 3) == 0  # short peek is a 0, not a crash


# ---------------------------------------------------------------------------
# fan-in + coalescing end-to-end


def _feed_queues(queues, gen, sizes, max_rows=50):
    """Deterministic drain schedule: per timestep, frames round-robin
    over the queues."""
    for t, n in enumerate(sizes):
        fb = gen.flow_batch(n, T0 + t)
        for i, fr in enumerate(
            encode_flowbatch_frames(fb, agent_id=t, max_rows_per_frame=max_rows)
        ):
            queues[(t + i) % len(queues)].put(fr)
        yield t


def test_feeder_fanin_matches_direct_ingest():
    """3-queue fan-in through the feeder produces bit-exact flushed
    windows vs direct pipeline ingest of the same per-timestep batches
    (pump-per-timestep keeps batch boundaries aligned)."""
    sizes = [150, 90, 256, 64, 200, 150, 30, 256, 110, 70]
    buckets = (64, 128, 256)

    gen = SyntheticFlowGen(num_tuples=200, seed=3)
    direct = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 12, stats_ring=4),
        batch_size=256, bucket_sizes=buckets,
    ))
    docs_direct = []
    for t, n in enumerate(sizes):
        docs_direct += direct.ingest(gen.flow_batch(n, T0 + t))
    docs_direct += direct.drain()

    gen2 = SyntheticFlowGen(num_tuples=200, seed=3)
    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 12, stats_ring=4),
        batch_size=256, bucket_sizes=buckets,
    ))
    queues = [PyOverwriteQueue(1 << 10) for _ in range(3)]
    feeder = FeederRuntime(
        queues, PipelineFeedSink(pipe), FeederConfig(frames_per_queue=8)
    )
    docs = []
    for _ in _feed_queues(queues, gen2, sizes):
        docs += feeder.pump()
    docs += feeder.flush()
    docs += pipe.drain()

    def rows(dbs):
        out = []
        for db in dbs:
            for i in range(db.size):
                out.append((int(db.timestamp[i]), tuple(db.tags[i].tolist()),
                            tuple(db.meters[i].tolist())))
        return sorted(out)

    assert rows(docs) == rows(docs_direct)
    fc = feeder.get_counters()
    assert fc["records_in"] == sum(sizes) == fc["records_out"]
    assert fc["shed_records"] == 0 and fc["bad_frames"] == 0
    pc = pipe.get_counters()
    assert pc["jit_retraces"] == 0
    assert pc["doc_in"] == direct.get_counters()["doc_in"]


def test_feeder_double_buffer_holds_one_batch():
    gen = SyntheticFlowGen(num_tuples=50, seed=5)
    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 10), batch_size=64,
        bucket_sizes=(64,),
    ))
    q = PyOverwriteQueue(64)
    sink = PipelineFeedSink(pipe)  # double_buffer=True
    feeder = FeederRuntime([q], sink, FeederConfig())
    for fr in encode_flowbatch_frames(gen.flow_batch(40, T0), max_rows_per_frame=40):
        q.put(fr)
    feeder.pump()
    # staged but not dispatched: the device hasn't seen the batch
    assert sink._held is not None
    assert pipe.get_counters()["doc_in"] == 0
    feeder.flush()
    pipe.wm.settle()
    assert sink._held is None
    assert pipe.get_counters()["doc_in"] > 0


def test_feeder_shed_deterministic_and_accounted():
    """Fixed drain schedule → identical shed decisions, counts and
    emitted batches across runs; every dropped record shows up in the
    feeder counters AND the pipeline's CB_FEEDER_SHED lane."""
    def run():
        gen = SyntheticFlowGen(num_tuples=20, seed=2)
        q = [PyOverwriteQueue(8)]
        pipe = L4Pipeline(PipelineConfig(
            window=WindowConfig(capacity=1 << 10), batch_size=64,
            bucket_sizes=(64,),
        ))
        feeder = FeederRuntime(
            q, PipelineFeedSink(pipe, double_buffer=False),
            FeederConfig(frames_per_queue=2, rounds_per_pump=1),
        )
        # overfill: 8 frames into a capacity-8 queue → depth ≥ high
        # watermark at the first visit
        for t in range(8):
            for fr in encode_flowbatch_frames(
                gen.flow_batch(10, T0 + t), max_rows_per_frame=10
            ):
                q[0].put(fr)
        feeder.pump()
        feeder.pump()
        feeder.flush()
        pipe.wm.settle()
        return feeder.get_counters(), pipe.get_counters()

    fc1, pc1 = run()
    fc2, pc2 = run()
    assert fc1 == fc2
    assert fc1["shed_frames"] > 0 and fc1["pressure_events"] > 0
    # whole frames only: shed records are a multiple of the frame size
    assert fc1["shed_records"] % 10 == 0
    # conservation: every record either ingested or accounted as shed
    assert fc1["records_in"] + fc1["shed_records"] == 80
    # the device counter block saw every shed record
    assert pc1["feeder_shed"] == fc1["shed_records"] == pc2["feeder_shed"]


def test_feeder_doc_sink_merges_like_device_path():
    """METRICS pb frames → WindowManagerFeedSink: host-side packed-word
    fingerprints must merge identical doc keys exactly like the device
    path (5 ports × 2 windows → 10 rows)."""
    from deepflow_tpu.datamodel.batch import DocBatch
    from deepflow_tpu.ingest.codec import encode_docbatch
    from deepflow_tpu.ingest.framing import FlowHeader, MessageType, encode_frame

    n = 40
    tags = np.zeros((n, TAG_SCHEMA.num_fields), np.uint32)
    tags[:, TAG_SCHEMA.index("meter_id")] = 1  # FLOW
    tags[:, TAG_SCHEMA.index("code_id")] = 1
    tags[:, TAG_SCHEMA.index("server_port")] = np.arange(n) % 5 + 80
    meters = np.zeros((n, FLOW_METER.num_fields), np.float32)
    meters[:, FLOW_METER.index("packet_tx")] = 1
    ts = np.full(n, T0, np.uint32)
    ts[n // 2:] = T0 + 5
    db = DocBatch(tags=tags, meters=meters, timestamp=ts,
                  valid=np.ones(n, bool))
    frame = encode_frame(
        FlowHeader(msg_type=int(MessageType.METRICS), agent_id=1),
        encode_docbatch(db),
    )

    wm = WindowManager(WindowConfig(capacity=1 << 10, stats_ring=4))
    q = PyOverwriteQueue(64)
    q.put(frame)
    feeder = FeederRuntime([q], WindowManagerFeedSink(wm, (32, 64)))
    flushed = feeder.pump()
    flushed += wm.flush_all()
    assert sum(f.count for f in flushed) == 10
    assert wm.get_counters()["doc_in"] == n
    # packet_tx mass conserved through the merge
    col = FLOW_METER.index("packet_tx")
    assert sum(float(f.meters[:, col].sum()) for f in flushed) == n


def test_feeder_sharded_sink_and_bucket_validation():
    from deepflow_tpu.feeder import ShardedFeedSink
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    mesh = make_mesh(2)
    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=6,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
    )
    swm = ShardedWindowManager(ShardedPipeline(mesh, cfg))
    with pytest.raises(ValueError, match="divisible"):
        ShardedFeedSink(swm, (63, 128))

    sizes = [100, 64, 120, 90, 100, 30]

    # direct oracle: same per-timestep batches, padded to the same
    # buckets, straight into a fresh manager
    gen0 = SyntheticFlowGen(num_tuples=100, seed=6)
    swm0 = ShardedWindowManager(ShardedPipeline(mesh, cfg))
    direct = []
    for t, n in enumerate(sizes):
        fb = gen0.flow_batch(n, T0 + t).pad_to(64 if n <= 64 else 128)
        direct += swm0.ingest(fb.tags, fb.meters, fb.valid)
    direct += swm0.drain()

    def rows(dbs):
        acc = []
        for db in dbs:
            for i in range(db.size):
                acc.append((int(db.timestamp[i]), tuple(db.tags[i].tolist()),
                            tuple(db.meters[i].tolist())))
        return sorted(acc)

    # (a) order-preserving fan-in (single queue): flushed rows BIT-EXACT
    # vs direct ingest — row order decides per-device stash assignment,
    # so this is the apples-to-apples sharded oracle
    gen = SyntheticFlowGen(num_tuples=100, seed=6)
    q = PyOverwriteQueue(256)
    feeder = FeederRuntime(
        [q], ShardedFeedSink(swm, (64, 128)), FeederConfig(frames_per_queue=8)
    )
    out = []
    for t in _feed_queues([q], gen, sizes, max_rows=40):
        out += feeder.pump()
    out += swm.drain()
    assert swm.get_counters()["flow_in"] == sum(sizes)
    assert rows(out) == rows(direct)

    # (b) true multi-queue fan-in permutes rows across devices (exact
    # stashes never merge cross-device — reference per-pipeline
    # isolation), so assert conservation: same row count and same total
    # per-window mass on a sum-merged meter column
    from deepflow_tpu.datamodel.schema import FLOW_METER as _M

    gen2 = SyntheticFlowGen(num_tuples=100, seed=6)
    swm2 = ShardedWindowManager(ShardedPipeline(mesh, cfg))
    queues = [PyOverwriteQueue(256) for _ in range(2)]
    feeder2 = FeederRuntime(
        queues, ShardedFeedSink(swm2, (64, 128)), FeederConfig(frames_per_queue=8)
    )
    out2 = []
    for t in _feed_queues(queues, gen2, sizes, max_rows=40):
        out2 += feeder2.pump()
    out2 += swm2.drain()
    col = _M.index("packet_tx")

    def mass(dbs):
        """Per-window (key set, sum-meter mass): both are invariant to
        the row permutation (a key split across devices flushes as two
        rows, but its identity and its summed meters are conserved)."""
        per_w = {}
        for db in dbs:
            w = int(db.timestamp[0])
            keys, tx = per_w.setdefault(w, (set(), 0.0))
            keys.update(tuple(db.tags[i].tolist()) for i in range(db.size))
            per_w[w] = (keys, tx + float(db.meters[:, col].sum()))
        return per_w

    assert mass(out2) == mass(direct)


def test_feeder_serve_thread_drains_queue():
    import time as _time

    gen = SyntheticFlowGen(num_tuples=30, seed=8)
    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 10), batch_size=64,
        bucket_sizes=(64,),
    ))
    q = PyOverwriteQueue(256)
    got = []
    feeder = FeederRuntime(
        [q], PipelineFeedSink(pipe, double_buffer=False), FeederConfig()
    )
    feeder.serve(poll_ms=5, on_flush=got.extend)
    try:
        for t in range(4):
            for fr in encode_flowbatch_frames(gen.flow_batch(50, T0 + t)):
                q.put(fr)
        deadline = _time.time() + 10
        while feeder.get_counters()["records_in"] < 200 and _time.time() < deadline:
            _time.sleep(0.02)
    finally:
        feeder.stop()
    assert feeder.get_counters()["records_in"] == 200


# ---------------------------------------------------------------------------
# satellites: queue counters, receiver closed-queue skip, checkpoint v1


def test_queue_counters_reach_stats_collector():
    from deepflow_tpu.utils.stats import StatsCollector

    col = StatsCollector()
    q = PyOverwriteQueue(2)
    # register on a private collector (not the process default)
    src = col.register("ingest_queue", q, msg_type="3", queue="0")
    q.put(b"a")
    q.put(b"b")
    q.put(b"c")  # overwrites oldest
    pts = col.tick()
    pt = [p for p in pts if p.module == "ingest_queue"][0]
    assert pt.fields["overwritten"] == 1
    assert pt.fields["depth"] == 2
    assert pt.fields["capacity"] == 2
    assert pt.fields["closed"] == 0
    q.close()
    assert col.tick()[0].fields["closed"] == 1
    col.deregister(src)


def test_receiver_registers_queue_stats_and_skips_closed(monkeypatch):
    from deepflow_tpu.ingest.framing import FlowHeader, MessageType, encode_frame
    from deepflow_tpu.ingest.receiver import Receiver
    from deepflow_tpu.utils import stats as stats_mod

    col = stats_mod.StatsCollector()
    monkeypatch.setattr(stats_mod, "default_collector", col)

    rx = Receiver()
    q_open, q_closed = PyOverwriteQueue(16), PyOverwriteQueue(16)
    rx.register_handler(MessageType.METRICS, [q_open, q_closed])
    q_closed.close()

    def frame(agent_id):
        return encode_frame(
            FlowHeader(msg_type=int(MessageType.METRICS), agent_id=agent_id),
            [b"\x08\x01"],
        )

    # agent 0 → queue 0 (open), agent 1 → queue 1 (closed)
    raw0, raw1 = frame(0), frame(1)
    from deepflow_tpu.ingest.framing import HEADER_LEN

    rx._dispatch(FlowHeader.parse(raw0[:HEADER_LEN]), raw0, ("t", 0))
    rx._dispatch(FlowHeader.parse(raw1[:HEADER_LEN]), raw1, ("t", 0))  # must NOT raise
    assert len(q_open) == 1
    assert rx.counters["queue_closed"] == 1
    assert rx.counters["rx_frames"] == 2
    # the registration satellite: both queues are live sources
    pts = [p for p in col.tick() if p.module == "ingest_queue"]
    assert len(pts) == 2
    assert {dict(p.tags)["queue"] for p in pts} == {"0", "1"}


def test_checkpoint_v1_load_is_a_clear_error(tmp_path):
    import io
    import json

    from deepflow_tpu.aggregator.checkpoint import load_window_state

    # a v1-shaped file (per-leaf arrays; the removed branch's input)
    meta = {"version": 1, "num_tags": TAG_SCHEMA.num_fields, "fill": 0,
            "start_window": None, "drop_before_window": 0,
            "total_docs_in": 0, "total_flushed": 0, "interval": 1,
            "delay": 2, "capacity": 64, "accum_batches": 8}
    buf = io.BytesIO()
    np.savez_compressed(
        buf, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        stash_slot=np.zeros(64, np.uint32),
    )
    p = tmp_path / "v1.ckpt"
    p.write_bytes(buf.getvalue())
    with pytest.raises(ValueError, match="v1.*unsupported|unsupported.*v1"):
        load_window_state(p, TAG_SCHEMA, FLOW_METER)
