"""The grand tour: a REAL agent daemon replays the reference's own
captures, ships over live TCP to a fully composed server, and every
query plane answers — the 'switch from the reference and find
everything' test."""

import os
import time

import numpy as np
import pytest

from deepflow_tpu.agent.main import Agent, AgentConfig
from deepflow_tpu.server.main import Server
from deepflow_tpu.utils.config import load_config

REF = "/root/reference/agent/resources/test/flow_generator"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference fixtures not present"
)


def _wait(cond, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


@pytest.mark.parametrize(
    "prefer_native,compression,n_agents",
    [
        (False, 0, 1),  # the r4 baseline scenario
        (True, 3, 2),   # native decoder + zstd framing + 2 concurrent agents
    ],
    ids=["python-plain-1agent", "native-zstd-2agents"],
)
def test_grand_tour(tmp_path, prefer_native, compression, n_agents):
    if prefer_native:
        from deepflow_tpu.native import native_available

        if not native_available():
            pytest.skip("native decode library not built")
    cfg, _ = load_config(
        {
            "receiver": {"tcp_port": 0, "udp_port": 0},
            "ingester": {"n_decoders": 1, "prefer_native": prefer_native},
            "storage": {"root": str(tmp_path / "store"), "writer_flush_s": 0.05},
        }
    )
    srv = Server(cfg, lease_path=tmp_path / "lease").start()
    agents: list[Agent] = []
    try:
        for k in range(n_agents):
            agents.append(Agent(
                AgentConfig(
                    agent_id=3 + k,
                    servers=(("127.0.0.1", srv.receiver.tcp_port),),
                    batch_size=512,
                    compression=compression,
                )
            ))
        agent = agents[0]
        # replay real captures spanning HTTP, DNS, MySQL, Redis traffic;
        # concurrent agents split the corpus, all shipping to one server
        import threading

        pcaps = ("http/httpv1.pcap", "dns/dns.pcap", "mysql/mysql.pcap",
                 "redis/redis.pcap")

        def replay(a, rels):
            for rel in rels:
                a.run_pcap(os.path.join(REF, rel))

        threads = [
            threading.Thread(target=replay, args=(a, pcaps[i::n_agents]))
            for i, a in enumerate(agents)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

        # l7 session count the agents actually shipped — wait until the
        # server has WRITTEN that many rows (sender flush + TCP + decode
        # are all async; querying earlier races the pipeline)
        l7_sent = sum(a.counters["logs_sent"] for a in agents)
        assert l7_sent > 0
        assert _wait(lambda: srv.flow_metrics.counters["docs_written"] > 0)
        srv.doc_writer.flush()

        def _table_rows(table):
            srv.flow_log.flush()
            try:
                return int(srv.query.execute(
                    f"SELECT Count() AS c FROM {table}").values["c"][0])
            except Exception:
                return 0

        # sender flush + TCP + decode + writer are all async — wait on
        # the QUERYABLE row counts, not on intermediate counters
        assert _wait(lambda: _table_rows("l7_flow_log") >= l7_sent)
        assert _wait(lambda: _table_rows("l4_flow_log") > 0)

        # 1. metrics plane answers SQL
        total = 0
        for table in ("network.1s", "network_map.1s", "network.1m", "network_map.1m"):
            try:
                total += int(srv.query.execute(
                    f"SELECT Count() AS c FROM {table}").values["c"][0])
            except Exception:
                pass
        assert total > 0

        # 2. L7 request logs landed with protocol fidelity
        r = srv.query.execute(
            "SELECT request_type, request_domain FROM l7_flow_log LIMIT 500")
        doms = set(str(d) for d in r.values["request_domain"])
        assert "rq.cct.cloud.duba.net" in doms  # from httpv1.pcap
        assert any("guoyongxin" in d or "yunshan" in d for d in doms)  # dns.pcap

        # 3. L4 flow logs (minute aggregation + throttle) landed — count
        # pinned above by the queryable-rows wait

        # 4. the agent syncs config/platform over the live trisolaris
        from deepflow_tpu.controller.trisolaris import AgentSyncClient

        srv.trisolaris.set_group_config("default", {"l4_log_collect_nps_threshold": 555})
        client = AgentSyncClient([("127.0.0.1", srv.trisolaris.port)], 3)
        assert client.sync_once()
        agent.apply_dynamic_config(client.config)
        assert agent.l4_throttle.throttle == 555
        assert client.analyzer_ip  # balancer assignment rode along

        # 5. multi-agent runs: rows arrived from every agent id (before
        # the housekeeping tick — the fixtures' decade-old timestamps
        # are TTL-expired the moment the monitor runs)
        if n_agents > 1:
            r = srv.query.execute(
                "SELECT agent_id, Count() AS c FROM l7_flow_log "
                "GROUP BY agent_id ORDER BY agent_id")
            assert len(r.values["agent_id"]) == n_agents, r.to_dicts()

        # 6. self-telemetry flowed
        did = srv.tick()
        assert "leader" in did
    finally:
        for a in agents:
            a.close()
        srv.stop()
