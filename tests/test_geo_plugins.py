"""geo lib, L7 plugin loader, eBPF L4 gate (SURVEY §2 parity items)."""

from __future__ import annotations

import numpy as np

from deepflow_tpu.utils.geo import BUILTIN_LABELS, GeoTable


def test_geo_builtin_ranges():
    g = GeoTable.builtin()
    ips = np.array(
        [0x0A000001, 0xAC100101, 0xC0A80001, 0x7F000001, 0x08080808, 0xE0000001],
        np.uint32,
    )
    got = [g.label(i) for i in g.lookup(ips)]
    assert got == ["private-10", "private-172", "private-192", "loopback",
                   "public", "multicast"]


def test_geo_custom_table():
    g = GeoTable.from_cidrs([("203.0.113.0/24", 42)], {42: "ap-southeast"})
    ids = g.lookup(np.array([0xCB007101, 0xCB007201], np.uint32))
    assert g.label(ids[0]) == "ap-southeast"
    assert ids[1] == 0  # outside the /24


def test_plugin_loader_registers_custom_protocol(tmp_path):
    from deepflow_tpu.agent.l7.parsers import infer_protocol, parse_payload
    from deepflow_tpu.agent.l7.plugins import load_plugins

    (tmp_path / "myproto.py").write_text(
        '''
from deepflow_tpu.agent.l7.parsers import L7Message, MSG_REQUEST

PROTOCOL = 201

def check_payload(payload, port=0):
    return payload.startswith(b"MYP/")

def parse_payload(payload):
    return L7Message(protocol=PROTOCOL, msg_type=MSG_REQUEST,
                     request_type=payload[4:8].decode(errors="replace"))
'''
    )
    (tmp_path / "broken.py").write_text("raise RuntimeError('bad plugin')")
    loaded = load_plugins(tmp_path)
    assert loaded == [(201, "myproto")]
    assert infer_protocol(b"MYP/PING hello") == 201
    assert parse_payload(201, b"MYP/PING").request_type == "PING"


def test_ebpf_flows_skip_l4_fanout():
    from deepflow_tpu.aggregator.fanout import FanoutConfig, fanout_l4, fanout_l7
    from deepflow_tpu.datamodel.code import SignalSource
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    gen = SyntheticFlowGen(num_tuples=16, seed=1)
    fb = gen.flow_batch(64, 1000)
    fb.tags["signal_source"][:] = int(SignalSource.EBPF)
    fb.tags["l7_protocol"][:] = 20
    import jax.numpy as jnp

    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    _t, _m, _ts, valid_l4 = fanout_l4(tags, jnp.asarray(fb.meters), jnp.asarray(fb.valid), FanoutConfig())
    assert not bool(np.asarray(valid_l4).any())  # no L4 docs from eBPF
    _t, _m, _ts, valid_l7 = fanout_l7(tags, jnp.asarray(fb.meters), jnp.asarray(fb.valid), FanoutConfig())
    assert bool(np.asarray(valid_l7).any())  # L7 plane still emits
