"""geo lib, L7 plugin loader, eBPF L4 gate (SURVEY §2 parity items)."""

from __future__ import annotations

import numpy as np

from deepflow_tpu.utils.geo import BUILTIN_LABELS, GeoTable


def test_geo_builtin_ranges():
    g = GeoTable.builtin()
    ips = np.array(
        [0x0A000001, 0xAC100101, 0xC0A80001, 0x7F000001, 0x08080808, 0xE0000001],
        np.uint32,
    )
    got = [g.label(i) for i in g.lookup(ips)]
    assert got == ["private-10", "private-172", "private-192", "loopback",
                   "public", "multicast"]


def test_geo_custom_table():
    g = GeoTable.from_cidrs([("203.0.113.0/24", 42)], {42: "ap-southeast"})
    ids = g.lookup(np.array([0xCB007101, 0xCB007201], np.uint32))
    assert g.label(ids[0]) == "ap-southeast"
    assert ids[1] == 0  # outside the /24


def test_plugin_loader_registers_custom_protocol(tmp_path):
    from deepflow_tpu.agent.l7.parsers import infer_protocol, parse_payload
    from deepflow_tpu.agent.l7.plugins import load_plugins

    (tmp_path / "myproto.py").write_text(
        '''
from deepflow_tpu.agent.l7.parsers import L7Message, MSG_REQUEST

PROTOCOL = 201

def check_payload(payload, port=0):
    return payload.startswith(b"MYP/")

def parse_payload(payload):
    return L7Message(protocol=PROTOCOL, msg_type=MSG_REQUEST,
                     request_type=payload[4:8].decode(errors="replace"))
'''
    )
    (tmp_path / "broken.py").write_text("raise RuntimeError('bad plugin')")
    loaded = load_plugins(tmp_path)
    assert loaded == [(201, "myproto")]
    assert infer_protocol(b"MYP/PING hello") == 201
    assert parse_payload(201, b"MYP/PING").request_type == "PING"


def test_ebpf_flows_skip_l4_fanout():
    from deepflow_tpu.aggregator.fanout import FanoutConfig, fanout_l4, fanout_l7
    from deepflow_tpu.datamodel.code import SignalSource
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    gen = SyntheticFlowGen(num_tuples=16, seed=1)
    fb = gen.flow_batch(64, 1000)
    fb.tags["signal_source"][:] = int(SignalSource.EBPF)
    fb.tags["l7_protocol"][:] = 20
    import jax.numpy as jnp

    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    _t, _m, _ts, valid_l4 = fanout_l4(tags, jnp.asarray(fb.meters), jnp.asarray(fb.valid), FanoutConfig())
    assert not bool(np.asarray(valid_l4).any())  # no L4 docs from eBPF
    _t, _m, _ts, valid_l7 = fanout_l7(tags, jnp.asarray(fb.meters), jnp.asarray(fb.valid), FanoutConfig())
    assert bool(np.asarray(valid_l7).any())  # L7 plane still emits


SO_PLUGIN_SRC = r"""
#include <string.h>

struct df_l7_info {
    int  msg_type;
    int  status;
    int  status_code;
    unsigned int request_id;
    char request_type[64];
    char request_resource[256];
    char request_domain[256];
    char endpoint[256];
};

int df_protocol(void) { return 211; }

int df_check(const unsigned char *payload, int len, int port) {
    (void)port;
    return len >= 4 && memcmp(payload, "NAT/", 4) == 0;
}

int df_parse(const unsigned char *payload, int len, struct df_l7_info *out) {
    if (!df_check(payload, len, 0)) return 0;
    memset(out, 0, sizeof(*out));
    out->msg_type = (len > 4 && payload[4] == 'R') ? 1 : 0;
    out->status = 1;
    out->status_code = 200;
    out->request_id = 7;
    strncpy(out->request_type, "CALL", sizeof(out->request_type) - 1);
    int n = len - 4 < 255 ? len - 4 : 255;
    memcpy(out->request_resource, payload + 4, n > 0 ? n : 0);
    return 1;
}
"""


def test_so_plugin_abi(tmp_path):
    """The native plugin seat: compile a real C parser against the
    documented ABI, load the .so, and drive it through the shared
    registry (reference: agent/src/plugin/shared_obj)."""
    import subprocess

    import pytest as _pytest

    from deepflow_tpu.agent.l7.parsers import infer_protocol, parse_payload
    from deepflow_tpu.agent.l7.plugins import load_plugins

    src = tmp_path / "natproto.c"
    src.write_text(SO_PLUGIN_SRC)
    so = tmp_path / "natproto.so"
    r = subprocess.run(
        ["gcc", "-shared", "-fPIC", "-O2", "-o", str(so), str(src)],
        capture_output=True,
    )
    if r.returncode != 0:
        _pytest.skip(f"gcc unavailable: {r.stderr.decode()[:120]}")
    (tmp_path / "broken.so").write_bytes(b"\x7fELFnot-really")

    loaded = load_plugins(tmp_path)
    assert (211, "natproto") in loaded
    assert all(name != "broken" for _, name in loaded)

    assert infer_protocol(b"NAT/lookup") == 211
    msg = parse_payload(211, b"NAT/lookup")
    assert msg.request_type == "CALL"
    assert msg.request_resource == "lookup"
    assert msg.request_id == 7 and msg.status_code == 200
    resp = parse_payload(211, b"NAT/R ok")
    from deepflow_tpu.agent.l7.parsers import MSG_RESPONSE

    assert resp.msg_type == MSG_RESPONSE
