"""Journaled recovery (ISSUE 6): kill-and-recover must be BIT-EXACT —
flushed window rows and the counter block — against an uninterrupted
oracle run, for kill-points before/during/after advance, flush and
checkpoint, single-chip and sharded. Plus the journal file format's
crash artifacts (torn tails, failed rotates) and the atomic+digested
checkpoint writer."""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from deepflow_tpu import chaos
from deepflow_tpu.aggregator.checkpoint import (
    load_window_state,
    read_checkpoint_meta,
    restore_sharded_state,
    save_sharded_state,
    save_window_state,
)
from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
from deepflow_tpu.aggregator.window import WindowConfig
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.feeder import (
    FeederConfig,
    FeederRuntime,
    FrameJournal,
    PipelineFeedSink,
    ShardedFeedSink,
    encode_flowbatch_frames,
    read_journal,
)
from deepflow_tpu.feeder.journal import REC_FRAME, REC_MARK
from deepflow_tpu.ingest.queues import PyOverwriteQueue
from deepflow_tpu.ingest.replay import SyntheticFlowGen

T0 = 1_700_000_000
BUCKETS = (64, 128, 256)

# the shared kill-and-recover schedule: two checkpoint barriers, window
# advances at known dispatch indices, a multi-window flush, final drain
STEPS = (
    ("batch", T0, 100),
    ("batch", T0 + 1, 120),
    ("ckpt",),
    ("batch", T0 + 5, 90),
    ("batch", T0 + 6, 110),
    ("ckpt",),
    ("batch", T0 + 7, 80),
    ("batch", T0 + 10, 100),
    ("drain",),
)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    chaos.uninstall()


_FRAMES = None
_ORACLES: dict = {}


def _frame_stream(seed=31):
    """Pre-encode every batch step's frames ONCE — oracle and victim
    must see byte-identical traffic (cached: every kill variant replays
    the same stream)."""
    global _FRAMES
    if _FRAMES is None:
        gen = SyntheticFlowGen(num_tuples=150, seed=seed)
        _FRAMES = {
            i: encode_flowbatch_frames(gen.flow_batch(n, t), max_rows_per_frame=64)
            for i, (kind, *args) in enumerate(STEPS)
            if kind == "batch"
            for t, n in (args,)
        }
    return _FRAMES


# -- contexts: the single-chip and sharded pipeline stacks ----------------


@dataclasses.dataclass
class _Ctx:
    q: object
    feeder: object
    save: object  # save(barrier) → outputs to emit
    drain: object  # () → final outputs
    restore: object  # () → load the checkpoint into this stack
    counters: object  # () → comparable logical counter dict
    ckpt: object  # checkpoint path


def _single_ctx(tmp, jname):
    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 12, delay=2),
        batch_size=256, bucket_sizes=BUCKETS,
    ))
    q = PyOverwriteQueue(1 << 12)
    feeder = FeederRuntime(
        [q], PipelineFeedSink(pipe), FeederConfig(frames_per_queue=128),
        journal=FrameJournal(tmp / jname),
    )
    ckpt = tmp / "wm.ckpt"

    def save(barrier):
        in_flight = save_window_state(pipe.wm, ckpt, extra_meta=barrier)
        return [pipe._to_docbatch(f) for f in in_flight]

    def restore():
        pipe.wm = load_window_state(ckpt, TAG_SCHEMA, FLOW_METER)

    def counters():
        c = pipe.get_counters()
        return {k: c[k] for k in (
            "doc_in", "flushed_doc", "drop_before_window", "prereduce_shed",
            "excess_word_hits", "stash_evictions", "window_advances",
            "feeder_shed",
        )}

    return _Ctx(q, feeder, save, pipe.drain, restore, counters, ckpt)


def _sharded_ctx(tmp, jname):
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=6,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
    )
    swm = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    q = PyOverwriteQueue(1 << 12)
    feeder = FeederRuntime(
        [q], ShardedFeedSink(swm, BUCKETS), FeederConfig(frames_per_queue=128),
        journal=FrameJournal(tmp / jname),
    )
    ckpt = tmp / "swm.ckpt"

    def save(barrier):
        return save_sharded_state(swm, ckpt, extra_meta=barrier)

    def restore():
        restore_sharded_state(swm, ckpt)

    def counters():
        c = swm.get_counters()
        return {k: c[k] for k in (
            "flow_in", "flushed_doc", "drop_before_window", "window_advances",
        )}

    return _Ctx(q, feeder, save, swm.drain, restore, counters, ckpt)


def _execute(ctx, frames, start=0):
    """Run STEPS[start:]; → (outputs in emission order, durable_count)
    where durable_count = outputs already covered by the last completed
    barrier (checkpoint or drain) — what a transactional downstream
    would have committed when a crash hits."""
    outputs, durable = [], 0
    for i in range(start, len(STEPS)):
        kind = STEPS[i][0]
        if kind == "batch":
            for fr in frames[i]:
                ctx.q.put(fr)
            outputs += ctx.feeder.pump()
        elif kind == "ckpt":
            outputs += ctx.feeder.checkpoint(ctx.save)
            durable = len(outputs)
        else:  # drain
            outputs += ctx.feeder.flush()
            outputs += ctx.drain()
            durable = len(outputs)
    return outputs, durable


def _assert_outputs_bit_exact(got, want):
    assert len(got) == len(want), (len(got), len(want))
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a.timestamp, b.timestamp)
        np.testing.assert_array_equal(a.tags, b.tags)
        assert a.meters.tobytes() == b.meters.tobytes()  # f32 bit-exact


def _oracle_for(tmp_path, mk_ctx):
    """The uninterrupted oracle run (journal active — identical code
    path). Cached per stack kind: every kill variant compares against
    the same stream, so one oracle serves the whole matrix."""
    key = mk_ctx.__name__
    if key not in _ORACLES:
        oracle_dir = tmp_path / "oracle"
        oracle_dir.mkdir()
        octx = mk_ctx(oracle_dir, "j.bin")
        out, _ = _execute(octx, _frame_stream())
        _ORACLES[key] = (out, octx.counters())
    return _ORACLES[key]


def _kill_and_recover(tmp_path, mk_ctx, plan, *, break_rotate=False):
    """Run the oracle; run a victim killed by `plan`; recover from
    checkpoint+journal; assert outputs and counters bit-exact."""
    frames = _frame_stream()
    oracle_out, oracle_c = _oracle_for(tmp_path, mk_ctx)

    # victim: same stream, killed mid-schedule
    victim_dir = tmp_path / "victim"
    victim_dir.mkdir()
    vctx = mk_ctx(victim_dir, "j1.bin")
    if break_rotate:
        # simulate a crash window between snapshot save and journal
        # rotate: the rotate never happens, so recovery must rely on
        # the (epoch, offset) barrier in the checkpoint meta
        vctx.feeder._journal.rotate = lambda: False
    outputs, durable, killed_at = [], 0, None
    chaos.install(plan)
    try:
        for i in range(len(STEPS)):
            kind = STEPS[i][0]
            try:
                if kind == "batch":
                    for fr in frames[i]:
                        vctx.q.put(fr)
                    outputs += vctx.feeder.pump()
                elif kind == "ckpt":
                    outputs += vctx.feeder.checkpoint(vctx.save)
                    durable = len(outputs)
                else:
                    outputs += vctx.feeder.flush()
                    outputs += vctx.drain()
                    durable = len(outputs)
            except chaos.KillPoint:
                killed_at = i
                break
    finally:
        chaos.uninstall()
    assert killed_at is not None, "the kill-point never fired"
    survivors = outputs[:durable]  # post-barrier outputs die with the process

    # recovery: ONLY disk state (checkpoint + journal) survives
    rctx = mk_ctx(victim_dir, "j2.bin")
    barrier = None
    if vctx.ckpt.exists():
        meta = read_checkpoint_meta(vctx.ckpt)
        if "journal_epoch" in meta:
            barrier = {
                "journal_epoch": meta["journal_epoch"],
                "journal_offset": meta["journal_offset"],
            }
        rctx.restore()
    recovered = rctx.feeder.replay_journal(victim_dir / "j1.bin", barrier=barrier)
    recovered += rctx.feeder.pump()  # completes the interrupted pump's tail
    rest, _ = _execute(rctx, frames, start=killed_at + 1)
    recovered += rest

    _assert_outputs_bit_exact(survivors + recovered, oracle_out)
    assert rctx.counters() == oracle_c
    return rctx


# -- the kill matrix ------------------------------------------------------
# Single-chip (double-buffered sink): dispatch indices 0..5; the T0+5
# batch's dispatch (idx 2) advances the span and flushes windows
# T0/T0+1; its flush-row fetch is host_fetch idx 5. Sharded (no double
# buffer): dispatch idx = batch ordinal; the T0+5 advance's packed-row
# block fetch is fetch idx 2.

_SINGLE_KILLS = {
    "pre_advance": chaos.FaultRule(chaos.SITE_DISPATCH, at=(2,), error=chaos.KillPoint()),
    "mid_flush": chaos.FaultRule(chaos.SITE_FETCH, at=(5,), error=chaos.KillPoint()),
    "during_ckpt": chaos.FaultRule(chaos.SITE_DISPATCH, at=(3,), error=chaos.KillPoint()),
    "post_ckpt": chaos.FaultRule(chaos.SITE_DISPATCH, at=(4,), error=chaos.KillPoint()),
}

_SHARDED_KILLS = {
    "pre_advance": chaos.FaultRule(chaos.SITE_DISPATCH, at=(2,), error=chaos.KillPoint()),
    "mid_flush": chaos.FaultRule(chaos.SITE_FETCH, at=(2,), error=chaos.KillPoint()),
    "post_ckpt": chaos.FaultRule(chaos.SITE_DISPATCH, at=(4,), error=chaos.KillPoint()),
}


@pytest.mark.parametrize("kill", sorted(_SINGLE_KILLS))
def test_kill_and_recover_single_chip_bit_exact(tmp_path, kill):
    _kill_and_recover(
        tmp_path, _single_ctx, chaos.FaultPlan().add(_SINGLE_KILLS[kill])
    )


@pytest.mark.parametrize("kill", sorted(_SHARDED_KILLS))
def test_kill_and_recover_sharded_bit_exact(tmp_path, kill):
    _kill_and_recover(
        tmp_path, _sharded_ctx, chaos.FaultPlan().add(_SHARDED_KILLS[kill])
    )


def test_kill_between_save_and_rotate_does_not_double_apply(tmp_path):
    """The nasty crash window: snapshot saved, journal NOT rotated. The
    journal still holds pre-barrier frames; replay must skip them via
    the (epoch, offset) barrier in the checkpoint meta or every
    checkpointed row double-counts."""
    rctx = _kill_and_recover(
        tmp_path, _single_ctx,
        chaos.FaultPlan().add(_SINGLE_KILLS["post_ckpt"]),
        break_rotate=True,
    )
    # the un-rotated journal really did hold pre-barrier frames —
    # i.e. the skip was exercised, not vacuous
    c = rctx.feeder.get_counters()
    assert c["replayed_frames"] > 0


def test_recovery_without_any_checkpoint(tmp_path):
    """Kill before the first checkpoint: recovery = full journal replay
    from an empty manager."""
    plan = chaos.FaultPlan().add(
        chaos.FaultRule(chaos.SITE_DISPATCH, at=(0,), error=chaos.KillPoint())
    )
    _kill_and_recover(tmp_path, _single_ctx, plan)


# -- journal file format --------------------------------------------------


def test_journal_roundtrip_and_torn_tail(tmp_path):
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    j.append(b"frame-one")
    j.append(b"frame-two")
    j.mark()
    j.append(b"frame-three")
    j.mark()
    j.close()

    epoch, entries, truncated = read_journal(p)
    assert epoch == 0 and not truncated
    assert [(k, pl) for k, pl, _ in entries] == [
        (REC_FRAME, b"frame-one"), (REC_FRAME, b"frame-two"), (REC_MARK, b""),
        (REC_FRAME, b"frame-three"), (REC_MARK, b""),
    ]

    # crash mid-write: a torn trailing record is detected and skipped,
    # the clean prefix survives. Cut into frame-three's record (13-byte
    # record header + 11-byte payload, then a 13-byte trailing MARK).
    data = p.read_bytes()
    p.write_bytes(data[:-20])
    epoch, entries, truncated = read_journal(p)
    assert truncated
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [
        b"frame-one", b"frame-two",
    ]

    # corrupt interior record: replay stops at it (never yields garbage)
    buf = bytearray(data)
    buf[len(data) // 2] ^= 0xFF
    p.write_bytes(bytes(buf))
    _, entries2, truncated2 = read_journal(p)
    assert truncated2 and len(entries2) < len(entries) + 3


def test_journal_reopen_truncates_torn_tail(tmp_path):
    """Reopening a journal after a crash-mid-record must truncate the
    torn tail before appending: records written after reopen would
    otherwise sit beyond the corruption and never replay."""
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    j.append(b"pre-crash")
    j.mark()
    j.close()
    data = p.read_bytes()
    p.write_bytes(data[:-5])  # tear into the trailing MARK record

    j2 = FrameJournal(p)  # the restarted process reuses the path
    assert j2.get_counters()["reopen_truncations"] == 1
    j2.append(b"post-restart")
    j2.mark()
    j2.close()

    epoch, entries, truncated = read_journal(p)
    assert not truncated  # the torn bytes are GONE, not buried
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [
        b"pre-crash", b"post-restart",
    ]


def test_journal_rotate_bumps_epoch_and_clears(tmp_path):
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    j.append(b"old")
    j.mark()
    epoch, off = j.sync_offset()
    assert epoch == 0 and off > 0
    assert j.rotate()
    j.append(b"new")
    j.mark()
    j.close()
    epoch, entries, truncated = read_journal(p)
    assert epoch == 1 and not truncated
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [b"new"]
    assert j.get_counters()["rotations"] == 1

    # re-open resumes the rotated epoch
    j2 = FrameJournal(p)
    assert j2.epoch == 1
    j2.close()


def test_journal_is_bounded(tmp_path):
    j = FrameJournal(tmp_path / "j.bin", max_bytes=256)
    blob = b"x" * 64
    appended = sum(1 for _ in range(20) if j.append(blob))
    j.close()
    c = j.get_counters()
    assert appended < 20  # the bound engaged
    assert c["overflow_frames"] == 20 - appended  # dropped, COUNTED
    assert c["frames"] == appended


def test_journal_io_faults_are_contained(tmp_path):
    j = FrameJournal(tmp_path / "j.bin")
    chaos.install(chaos.FaultPlan().add(
        chaos.FaultRule(chaos.SITE_JOURNAL_IO, at=(1,),
                        error=chaos.CheckpointIOError)
    ))
    assert j.append(b"ok")  # idx 0: fine
    assert not j.append(b"lost")  # idx 1: injected I/O error, contained
    assert j.append(b"ok2")
    chaos.uninstall()
    j.mark()
    j.close()
    assert j.get_counters()["io_errors"] == 1
    _, entries, _ = read_journal(tmp_path / "j.bin")
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [b"ok", b"ok2"]


def test_replay_respects_barrier_offset(tmp_path):
    """Unit-level barrier skip: frames before the checkpoint's
    (epoch, offset) never reach the decode path on replay."""
    frames = _frame_stream()
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    for fr in frames[0]:
        j.append(fr)
    j.mark()
    epoch, off = j.sync_offset()
    for fr in frames[1]:
        j.append(fr)
    j.mark()
    j.close()

    ctx = _single_ctx(tmp_path, "j2.bin")
    ctx.feeder.replay_journal(
        p, barrier={"journal_epoch": epoch, "journal_offset": off}
    )
    c = ctx.feeder.get_counters()
    assert c["replayed_frames"] == len(frames[1])
    assert c["records_in"] == 120  # only step 1's rows


def test_replay_from_own_journal_path_does_not_duplicate(tmp_path):
    """The natural fixed-path restart: the recovered runtime opens its
    journal at the SAME path it replays. The live journal must rotate
    before re-appending, or every frame sits twice in one epoch and a
    second crash double-applies them all."""
    frames = _frame_stream()
    ctx = _single_ctx(tmp_path, "j.bin")
    for i in (0, 1):
        for fr in frames[i]:
            ctx.q.put(fr)
        ctx.feeder.pump()
    ctx.feeder._journal.close()  # crash

    ctx2 = _single_ctx(tmp_path, "j.bin")  # SAME journal path
    ctx2.feeder.replay_journal(tmp_path / "j.bin")
    c = ctx2.feeder.get_counters()
    assert c["replayed_frames"] == len(frames[0]) + len(frames[1])
    ctx2.feeder._journal.close()

    epoch, entries, truncated = read_journal(tmp_path / "j.bin")
    assert epoch == 1 and not truncated  # rotated, then re-journaled
    payloads = [pl for k, pl, _ in entries if k == REC_FRAME]
    assert len(payloads) == c["replayed_frames"]  # each frame ONCE
    assert len(set(payloads)) == len(payloads)


# -- atomic + digested checkpoints ---------------------------------------


def _small_pipe():
    return L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 12), batch_size=256,
    ))


def test_checkpoint_truncation_fails_loudly(tmp_path):
    """Regression for the mid-write-kill failure mode of the old
    non-atomic writer: a torn checkpoint file must produce a clear
    error, not a numpy/zipfile traceback."""
    gen = SyntheticFlowGen(num_tuples=40, seed=7)
    from deepflow_tpu.datamodel.batch import FlowBatch

    pipe = _small_pipe()
    pipe.ingest(FlowBatch.from_records(gen.records(100, T0)))
    p = tmp_path / "wm.ckpt"
    # a MISSING file stays FileNotFoundError (cold start, not corruption)
    with pytest.raises(FileNotFoundError):
        read_checkpoint_meta(tmp_path / "nope.ckpt")
    save_window_state(pipe.wm, p)
    data = p.read_bytes()
    for cut in (10, len(data) // 2, len(data) - 3):
        p.write_bytes(data[:cut])
        with pytest.raises(ValueError, match="truncated or corrupt"):
            load_window_state(p, TAG_SCHEMA, FLOW_METER)
        # the meta-only reader keeps the same loud contract
        with pytest.raises(ValueError, match="truncated or corrupt"):
            read_checkpoint_meta(p)
    # no stray temp file from the atomic writer
    assert not (tmp_path / "wm.ckpt.tmp").exists()


def test_checkpoint_digest_mismatch_fails_loudly(tmp_path):
    import io
    import json

    gen = SyntheticFlowGen(num_tuples=40, seed=7)
    from deepflow_tpu.datamodel.batch import FlowBatch

    pipe = _small_pipe()
    pipe.ingest(FlowBatch.from_records(gen.records(100, T0)))
    p = tmp_path / "wm.ckpt"
    save_window_state(pipe.wm, p)

    # rebuild a VALID npz whose arrays were tampered with but whose
    # meta (and digest) are stale — zipfile CRCs pass, the content
    # digest must not
    with np.load(io.BytesIO(p.read_bytes())) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        arrays = {k: np.asarray(z[k]) for k in z.files if k != "meta"}
    arrays["stash_packed"] = np.zeros_like(arrays["stash_packed"])
    buf = io.BytesIO()
    np.savez_compressed(
        buf, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8), **arrays
    )
    p.write_bytes(buf.getvalue())
    with pytest.raises(ValueError, match="digest mismatch"):
        load_window_state(p, TAG_SCHEMA, FLOW_METER)


def test_sharded_checkpoint_roundtrip(tmp_path):
    """Sharded save/restore alone (no journal): open windows survive,
    meter mass conserved, wrong-mesh restore fails loudly."""
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=6,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
    )

    def mk(n_dev=2):
        return ShardedWindowManager(ShardedPipeline(make_mesh(n_dev), cfg))

    gen = SyntheticFlowGen(num_tuples=80, seed=19)
    stream = [(T0, 128), (T0 + 1, 128), (T0 + 6, 128), (T0 + 7, 64)]

    def run(save_after):
        g = SyntheticFlowGen(num_tuples=80, seed=19)
        swm = mk()
        docs = []
        for i, (t, n) in enumerate(stream):
            fb = g.flow_batch(n, t)
            docs += swm.ingest(fb.tags, fb.meters, fb.valid)
            if save_after == i:
                save_sharded_state(swm, tmp_path / "swm.ckpt")
                swm = mk()
                restore_sharded_state(swm, tmp_path / "swm.ckpt")
        docs += swm.drain()
        c = FLOW_METER.index("packet_tx")
        return (sum(float(db.meters[:, c].sum()) for db in docs),
                sum(db.size for db in docs))

    assert run(save_after=1) == run(save_after=None)

    # device-count mismatch must fail loudly, not mis-split
    swm4 = mk(4)
    with pytest.raises(ValueError, match="devices"):
        restore_sharded_state(swm4, tmp_path / "swm.ckpt")

    # window-timing mismatch must fail loudly too: start_window /
    # drop_before_window are indices in units of interval and would be
    # silently reinterpreted under a different delay/interval
    from deepflow_tpu.parallel.sharded import ShardedWindowManager as _SWM
    from deepflow_tpu.parallel.mesh import make_mesh as _mm
    from deepflow_tpu.parallel.sharded import ShardedPipeline as _SP

    with pytest.raises(ValueError, match="window timing"):
        restore_sharded_state(
            _SWM(_SP(_mm(2), cfg), delay=5), tmp_path / "swm.ckpt"
        )

    # capacity mismatch: the stash S dim disagrees with the compiled
    # config — loud error, not a downstream shape blowup
    cfg_small = dataclasses.replace(cfg, capacity_per_device=1 << 9)
    with pytest.raises(ValueError, match="capacity_per_device"):
        restore_sharded_state(
            _SWM(_SP(_mm(2), cfg_small)), tmp_path / "swm.ckpt"
        )
