"""Journaled recovery (ISSUE 6): kill-and-recover must be BIT-EXACT —
flushed window rows and the counter block — against an uninterrupted
oracle run, for kill-points before/during/after advance, flush and
checkpoint, single-chip and sharded. Plus the journal file format's
crash artifacts (torn tails, failed rotates) and the atomic+digested
checkpoint writer."""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from deepflow_tpu import chaos
from deepflow_tpu.aggregator.checkpoint import (
    load_window_state,
    read_checkpoint_meta,
    restore_sharded_state,
    save_sharded_state,
    save_window_state,
)
from deepflow_tpu.aggregator.pipeline import L4Pipeline, PipelineConfig
from deepflow_tpu.aggregator.window import WindowConfig
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.feeder import (
    FeederConfig,
    FeederRuntime,
    FrameJournal,
    PipelineFeedSink,
    ShardedFeedSink,
    encode_flowbatch_frames,
    read_journal,
)
from deepflow_tpu.feeder.journal import REC_FRAME, REC_MARK
from deepflow_tpu.ingest.queues import PyOverwriteQueue
from deepflow_tpu.ingest.replay import SyntheticFlowGen

T0 = 1_700_000_000
BUCKETS = (64, 128, 256)

# the shared kill-and-recover schedule: two checkpoint barriers, window
# advances at known dispatch indices, a multi-window flush, final drain
STEPS = (
    ("batch", T0, 100),
    ("batch", T0 + 1, 120),
    ("ckpt",),
    ("batch", T0 + 5, 90),
    ("batch", T0 + 6, 110),
    ("ckpt",),
    ("batch", T0 + 7, 80),
    ("batch", T0 + 10, 100),
    ("drain",),
)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    chaos.uninstall()


_FRAMES = None
_ORACLES: dict = {}


def _frame_stream(seed=31):
    """Pre-encode every batch step's frames ONCE — oracle and victim
    must see byte-identical traffic (cached: every kill variant replays
    the same stream)."""
    global _FRAMES
    if _FRAMES is None:
        gen = SyntheticFlowGen(num_tuples=150, seed=seed)
        _FRAMES = {
            i: encode_flowbatch_frames(gen.flow_batch(n, t), max_rows_per_frame=64)
            for i, (kind, *args) in enumerate(STEPS)
            if kind == "batch"
            for t, n in (args,)
        }
    return _FRAMES


# -- contexts: the single-chip and sharded pipeline stacks ----------------


@dataclasses.dataclass
class _Ctx:
    q: object
    feeder: object
    save: object  # save(barrier) → outputs to emit
    drain: object  # () → final outputs
    restore: object  # () → load the checkpoint into this stack
    counters: object  # () → comparable logical counter dict
    ckpt: object  # checkpoint path


def _single_ctx(tmp, jname):
    pipe = L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 12, delay=2),
        batch_size=256, bucket_sizes=BUCKETS,
    ))
    q = PyOverwriteQueue(1 << 12)
    feeder = FeederRuntime(
        [q], PipelineFeedSink(pipe), FeederConfig(frames_per_queue=128),
        journal=FrameJournal(tmp / jname),
    )
    ckpt = tmp / "wm.ckpt"

    def save(barrier):
        in_flight = save_window_state(pipe.wm, ckpt, extra_meta=barrier)
        return [pipe._to_docbatch(f) for f in in_flight]

    def restore():
        pipe.wm = load_window_state(ckpt, TAG_SCHEMA, FLOW_METER)

    def counters():
        c = pipe.get_counters()
        return {k: c[k] for k in (
            "doc_in", "flushed_doc", "drop_before_window", "prereduce_shed",
            "excess_word_hits", "stash_evictions", "window_advances",
            "feeder_shed",
        )}

    return _Ctx(q, feeder, save, pipe.drain, restore, counters, ckpt)


def _sharded_ctx(tmp, jname):
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=6,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
    )
    swm = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    q = PyOverwriteQueue(1 << 12)
    feeder = FeederRuntime(
        [q], ShardedFeedSink(swm, BUCKETS), FeederConfig(frames_per_queue=128),
        journal=FrameJournal(tmp / jname),
    )
    ckpt = tmp / "swm.ckpt"

    def save(barrier):
        return save_sharded_state(swm, ckpt, extra_meta=barrier)

    def restore():
        restore_sharded_state(swm, ckpt)

    def counters():
        c = swm.get_counters()
        return {k: c[k] for k in (
            "flow_in", "flushed_doc", "drop_before_window", "window_advances",
        )}

    return _Ctx(q, feeder, save, swm.drain, restore, counters, ckpt)


def _execute(ctx, frames, start=0):
    """Run STEPS[start:]; → (outputs in emission order, durable_count)
    where durable_count = outputs already covered by the last completed
    barrier (checkpoint or drain) — what a transactional downstream
    would have committed when a crash hits."""
    outputs, durable = [], 0
    for i in range(start, len(STEPS)):
        kind = STEPS[i][0]
        if kind == "batch":
            for fr in frames[i]:
                ctx.q.put(fr)
            outputs += ctx.feeder.pump()
        elif kind == "ckpt":
            outputs += ctx.feeder.checkpoint(ctx.save)
            durable = len(outputs)
        else:  # drain
            outputs += ctx.feeder.flush()
            outputs += ctx.drain()
            durable = len(outputs)
    return outputs, durable


def _assert_outputs_bit_exact(got, want):
    assert len(got) == len(want), (len(got), len(want))
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a.timestamp, b.timestamp)
        np.testing.assert_array_equal(a.tags, b.tags)
        assert a.meters.tobytes() == b.meters.tobytes()  # f32 bit-exact


def _oracle_for(tmp_path, mk_ctx):
    """The uninterrupted oracle run (journal active — identical code
    path). Cached per stack kind: every kill variant compares against
    the same stream, so one oracle serves the whole matrix."""
    key = mk_ctx.__name__
    if key not in _ORACLES:
        oracle_dir = tmp_path / "oracle"
        oracle_dir.mkdir()
        octx = mk_ctx(oracle_dir, "j.bin")
        out, _ = _execute(octx, _frame_stream())
        _ORACLES[key] = (out, octx.counters())
    return _ORACLES[key]


def _kill_and_recover(tmp_path, mk_ctx, plan, *, break_rotate=False):
    """Run the oracle; run a victim killed by `plan`; recover from
    checkpoint+journal; assert outputs and counters bit-exact."""
    frames = _frame_stream()
    oracle_out, oracle_c = _oracle_for(tmp_path, mk_ctx)

    # victim: same stream, killed mid-schedule
    victim_dir = tmp_path / "victim"
    victim_dir.mkdir()
    vctx = mk_ctx(victim_dir, "j1.bin")
    if break_rotate:
        # simulate a crash window between snapshot save and journal
        # rotate: the rotate never happens, so recovery must rely on
        # the (epoch, offset) barrier in the checkpoint meta
        vctx.feeder._journal.rotate = lambda: False
    outputs, durable, killed_at = [], 0, None
    chaos.install(plan)
    try:
        for i in range(len(STEPS)):
            kind = STEPS[i][0]
            try:
                if kind == "batch":
                    for fr in frames[i]:
                        vctx.q.put(fr)
                    outputs += vctx.feeder.pump()
                elif kind == "ckpt":
                    outputs += vctx.feeder.checkpoint(vctx.save)
                    durable = len(outputs)
                else:
                    outputs += vctx.feeder.flush()
                    outputs += vctx.drain()
                    durable = len(outputs)
            except chaos.KillPoint:
                killed_at = i
                break
    finally:
        chaos.uninstall()
    assert killed_at is not None, "the kill-point never fired"
    survivors = outputs[:durable]  # post-barrier outputs die with the process

    # recovery: ONLY disk state (checkpoint + journal) survives
    rctx = mk_ctx(victim_dir, "j2.bin")
    barrier = None
    if vctx.ckpt.exists():
        meta = read_checkpoint_meta(vctx.ckpt)
        if "journal_epoch" in meta:
            barrier = {
                "journal_epoch": meta["journal_epoch"],
                "journal_offset": meta["journal_offset"],
            }
        rctx.restore()
    recovered = rctx.feeder.replay_journal(victim_dir / "j1.bin", barrier=barrier)
    recovered += rctx.feeder.pump()  # completes the interrupted pump's tail
    rest, _ = _execute(rctx, frames, start=killed_at + 1)
    recovered += rest

    _assert_outputs_bit_exact(survivors + recovered, oracle_out)
    assert rctx.counters() == oracle_c
    return rctx


# -- the kill matrix ------------------------------------------------------
# Single-chip (double-buffered sink): dispatch indices 0..5; the T0+5
# batch's dispatch (idx 2) advances the span and flushes windows
# T0/T0+1; its flush-row fetch is host_fetch idx 5. Sharded (no double
# buffer): dispatch idx = batch ordinal; the T0+5 advance's packed-row
# block fetch is fetch idx 2.

_SINGLE_KILLS = {
    "pre_advance": chaos.FaultRule(chaos.SITE_DISPATCH, at=(2,), error=chaos.KillPoint()),
    "mid_flush": chaos.FaultRule(chaos.SITE_FETCH, at=(5,), error=chaos.KillPoint()),
    "during_ckpt": chaos.FaultRule(chaos.SITE_DISPATCH, at=(3,), error=chaos.KillPoint()),
    "post_ckpt": chaos.FaultRule(chaos.SITE_DISPATCH, at=(4,), error=chaos.KillPoint()),
}

_SHARDED_KILLS = {
    "pre_advance": chaos.FaultRule(chaos.SITE_DISPATCH, at=(2,), error=chaos.KillPoint()),
    "mid_flush": chaos.FaultRule(chaos.SITE_FETCH, at=(2,), error=chaos.KillPoint()),
    "post_ckpt": chaos.FaultRule(chaos.SITE_DISPATCH, at=(4,), error=chaos.KillPoint()),
}


@pytest.mark.parametrize("kill", sorted(_SINGLE_KILLS))
def test_kill_and_recover_single_chip_bit_exact(tmp_path, kill):
    _kill_and_recover(
        tmp_path, _single_ctx, chaos.FaultPlan().add(_SINGLE_KILLS[kill])
    )


@pytest.mark.parametrize("kill", sorted(_SHARDED_KILLS))
def test_kill_and_recover_sharded_bit_exact(tmp_path, kill):
    _kill_and_recover(
        tmp_path, _sharded_ctx, chaos.FaultPlan().add(_SHARDED_KILLS[kill])
    )


def test_kill_between_save_and_rotate_does_not_double_apply(tmp_path):
    """The nasty crash window: snapshot saved, journal NOT rotated. The
    journal still holds pre-barrier frames; replay must skip them via
    the (epoch, offset) barrier in the checkpoint meta or every
    checkpointed row double-counts."""
    rctx = _kill_and_recover(
        tmp_path, _single_ctx,
        chaos.FaultPlan().add(_SINGLE_KILLS["post_ckpt"]),
        break_rotate=True,
    )
    # the un-rotated journal really did hold pre-barrier frames —
    # i.e. the skip was exercised, not vacuous
    c = rctx.feeder.get_counters()
    assert c["replayed_frames"] > 0


def test_recovery_without_any_checkpoint(tmp_path):
    """Kill before the first checkpoint: recovery = full journal replay
    from an empty manager."""
    plan = chaos.FaultPlan().add(
        chaos.FaultRule(chaos.SITE_DISPATCH, at=(0,), error=chaos.KillPoint())
    )
    _kill_and_recover(tmp_path, _single_ctx, plan)


# -- journal file format --------------------------------------------------


def test_journal_roundtrip_and_torn_tail(tmp_path):
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    j.append(b"frame-one")
    j.append(b"frame-two")
    j.mark()
    j.append(b"frame-three")
    j.mark()
    j.close()

    epoch, entries, truncated = read_journal(p)
    assert epoch == 0 and not truncated
    assert [(k, pl) for k, pl, _ in entries] == [
        (REC_FRAME, b"frame-one"), (REC_FRAME, b"frame-two"), (REC_MARK, b""),
        (REC_FRAME, b"frame-three"), (REC_MARK, b""),
    ]

    # crash mid-write: a torn trailing record is detected and skipped,
    # the clean prefix survives. Cut into frame-three's record (13-byte
    # record header + 11-byte payload, then a 13-byte trailing MARK).
    data = p.read_bytes()
    p.write_bytes(data[:-20])
    epoch, entries, truncated = read_journal(p)
    assert truncated
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [
        b"frame-one", b"frame-two",
    ]

    # corrupt interior record: replay stops at it (never yields garbage)
    buf = bytearray(data)
    buf[len(data) // 2] ^= 0xFF
    p.write_bytes(bytes(buf))
    _, entries2, truncated2 = read_journal(p)
    assert truncated2 and len(entries2) < len(entries) + 3


def test_journal_reopen_truncates_torn_tail(tmp_path):
    """Reopening a journal after a crash-mid-record must truncate the
    torn tail before appending: records written after reopen would
    otherwise sit beyond the corruption and never replay."""
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    j.append(b"pre-crash")
    j.mark()
    j.close()
    data = p.read_bytes()
    p.write_bytes(data[:-5])  # tear into the trailing MARK record

    j2 = FrameJournal(p)  # the restarted process reuses the path
    assert j2.get_counters()["reopen_truncations"] == 1
    j2.append(b"post-restart")
    j2.mark()
    j2.close()

    epoch, entries, truncated = read_journal(p)
    assert not truncated  # the torn bytes are GONE, not buried
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [
        b"pre-crash", b"post-restart",
    ]


def test_journal_rotate_bumps_epoch_and_clears(tmp_path):
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    j.append(b"old")
    j.mark()
    epoch, off = j.sync_offset()
    assert epoch == 0 and off > 0
    assert j.rotate()
    j.append(b"new")
    j.mark()
    j.close()
    epoch, entries, truncated = read_journal(p)
    assert epoch == 1 and not truncated
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [b"new"]
    assert j.get_counters()["rotations"] == 1

    # re-open resumes the rotated epoch
    j2 = FrameJournal(p)
    assert j2.epoch == 1
    j2.close()


def test_journal_is_bounded(tmp_path):
    j = FrameJournal(tmp_path / "j.bin", max_bytes=256)
    blob = b"x" * 64
    appended = sum(1 for _ in range(20) if j.append(blob))
    j.close()
    c = j.get_counters()
    assert appended < 20  # the bound engaged
    assert c["overflow_frames"] == 20 - appended  # dropped, COUNTED
    assert c["frames"] == appended


def test_journal_io_faults_are_contained(tmp_path):
    j = FrameJournal(tmp_path / "j.bin")
    chaos.install(chaos.FaultPlan().add(
        chaos.FaultRule(chaos.SITE_JOURNAL_IO, at=(1,),
                        error=chaos.CheckpointIOError)
    ))
    assert j.append(b"ok")  # idx 0: fine
    assert not j.append(b"lost")  # idx 1: injected I/O error, contained
    assert j.append(b"ok2")
    chaos.uninstall()
    j.mark()
    j.close()
    assert j.get_counters()["io_errors"] == 1
    _, entries, _ = read_journal(tmp_path / "j.bin")
    assert [pl for k, pl, _ in entries if k == REC_FRAME] == [b"ok", b"ok2"]


def test_replay_respects_barrier_offset(tmp_path):
    """Unit-level barrier skip: frames before the checkpoint's
    (epoch, offset) never reach the decode path on replay."""
    frames = _frame_stream()
    p = tmp_path / "j.bin"
    j = FrameJournal(p)
    for fr in frames[0]:
        j.append(fr)
    j.mark()
    epoch, off = j.sync_offset()
    for fr in frames[1]:
        j.append(fr)
    j.mark()
    j.close()

    ctx = _single_ctx(tmp_path, "j2.bin")
    ctx.feeder.replay_journal(
        p, barrier={"journal_epoch": epoch, "journal_offset": off}
    )
    c = ctx.feeder.get_counters()
    assert c["replayed_frames"] == len(frames[1])
    assert c["records_in"] == 120  # only step 1's rows


def test_replay_from_own_journal_path_does_not_duplicate(tmp_path):
    """The natural fixed-path restart: the recovered runtime opens its
    journal at the SAME path it replays. The live journal must rotate
    before re-appending, or every frame sits twice in one epoch and a
    second crash double-applies them all."""
    frames = _frame_stream()
    ctx = _single_ctx(tmp_path, "j.bin")
    for i in (0, 1):
        for fr in frames[i]:
            ctx.q.put(fr)
        ctx.feeder.pump()
    ctx.feeder._journal.close()  # crash

    ctx2 = _single_ctx(tmp_path, "j.bin")  # SAME journal path
    ctx2.feeder.replay_journal(tmp_path / "j.bin")
    c = ctx2.feeder.get_counters()
    assert c["replayed_frames"] == len(frames[0]) + len(frames[1])
    ctx2.feeder._journal.close()

    epoch, entries, truncated = read_journal(tmp_path / "j.bin")
    assert epoch == 1 and not truncated  # rotated, then re-journaled
    payloads = [pl for k, pl, _ in entries if k == REC_FRAME]
    assert len(payloads) == c["replayed_frames"]  # each frame ONCE
    assert len(set(payloads)) == len(payloads)


# -- atomic + digested checkpoints ---------------------------------------


def _small_pipe():
    return L4Pipeline(PipelineConfig(
        window=WindowConfig(capacity=1 << 12), batch_size=256,
    ))


def test_checkpoint_truncation_fails_loudly(tmp_path):
    """Regression for the mid-write-kill failure mode of the old
    non-atomic writer: a torn checkpoint file must produce a clear
    error, not a numpy/zipfile traceback."""
    gen = SyntheticFlowGen(num_tuples=40, seed=7)
    from deepflow_tpu.datamodel.batch import FlowBatch

    pipe = _small_pipe()
    pipe.ingest(FlowBatch.from_records(gen.records(100, T0)))
    p = tmp_path / "wm.ckpt"
    # a MISSING file stays FileNotFoundError (cold start, not corruption)
    with pytest.raises(FileNotFoundError):
        read_checkpoint_meta(tmp_path / "nope.ckpt")
    save_window_state(pipe.wm, p)
    data = p.read_bytes()
    for cut in (10, len(data) // 2, len(data) - 3):
        p.write_bytes(data[:cut])
        with pytest.raises(ValueError, match="truncated or corrupt"):
            load_window_state(p, TAG_SCHEMA, FLOW_METER)
        # the meta-only reader keeps the same loud contract
        with pytest.raises(ValueError, match="truncated or corrupt"):
            read_checkpoint_meta(p)
    # no stray temp file from the atomic writer
    assert not (tmp_path / "wm.ckpt.tmp").exists()


def test_checkpoint_digest_mismatch_fails_loudly(tmp_path):
    import io
    import json

    gen = SyntheticFlowGen(num_tuples=40, seed=7)
    from deepflow_tpu.datamodel.batch import FlowBatch

    pipe = _small_pipe()
    pipe.ingest(FlowBatch.from_records(gen.records(100, T0)))
    p = tmp_path / "wm.ckpt"
    save_window_state(pipe.wm, p)

    # rebuild a VALID npz whose arrays were tampered with but whose
    # meta (and digest) are stale — zipfile CRCs pass, the content
    # digest must not
    with np.load(io.BytesIO(p.read_bytes())) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        arrays = {k: np.asarray(z[k]) for k in z.files if k != "meta"}
    arrays["stash_packed"] = np.zeros_like(arrays["stash_packed"])
    buf = io.BytesIO()
    np.savez_compressed(
        buf, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8), **arrays
    )
    p.write_bytes(buf.getvalue())
    with pytest.raises(ValueError, match="digest mismatch"):
        load_window_state(p, TAG_SCHEMA, FLOW_METER)


def test_sharded_checkpoint_roundtrip(tmp_path):
    """Sharded save/restore alone (no journal): open windows survive,
    meter mass conserved, wrong-mesh restore fails loudly."""
    from deepflow_tpu.ops.histogram import LogHistSpec
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=16, hll_precision=6,
        hist=LogHistSpec(bins=64, vmin=1.0, gamma=1.3),
    )

    def mk(n_dev=2):
        return ShardedWindowManager(ShardedPipeline(make_mesh(n_dev), cfg))

    gen = SyntheticFlowGen(num_tuples=80, seed=19)
    stream = [(T0, 128), (T0 + 1, 128), (T0 + 6, 128), (T0 + 7, 64)]

    def run(save_after):
        g = SyntheticFlowGen(num_tuples=80, seed=19)
        swm = mk()
        docs = []
        for i, (t, n) in enumerate(stream):
            fb = g.flow_batch(n, t)
            docs += swm.ingest(fb.tags, fb.meters, fb.valid)
            if save_after == i:
                save_sharded_state(swm, tmp_path / "swm.ckpt")
                swm = mk()
                restore_sharded_state(swm, tmp_path / "swm.ckpt")
        docs += swm.drain()
        c = FLOW_METER.index("packet_tx")
        return (sum(float(db.meters[:, c].sum()) for db in docs),
                sum(db.size for db in docs))

    assert run(save_after=1) == run(save_after=None)

    # device-count mismatch must fail loudly, not mis-split
    swm4 = mk(4)
    with pytest.raises(ValueError, match="devices"):
        restore_sharded_state(swm4, tmp_path / "swm.ckpt")

    # window-timing mismatch must fail loudly too: start_window /
    # drop_before_window are indices in units of interval and would be
    # silently reinterpreted under a different delay/interval
    from deepflow_tpu.parallel.sharded import ShardedWindowManager as _SWM
    from deepflow_tpu.parallel.mesh import make_mesh as _mm
    from deepflow_tpu.parallel.sharded import ShardedPipeline as _SP

    with pytest.raises(ValueError, match="window timing"):
        restore_sharded_state(
            _SWM(_SP(_mm(2), cfg), delay=5), tmp_path / "swm.ckpt"
        )

    # capacity mismatch: the stash S dim disagrees with the compiled
    # config — loud error, not a downstream shape blowup
    cfg_small = dataclasses.replace(cfg, capacity_per_device=1 << 9)
    with pytest.raises(ValueError, match="capacity_per_device"):
        restore_sharded_state(
            _SWM(_SP(_mm(2), cfg_small)), tmp_path / "swm.ckpt"
        )


# ---------------------------------------------------------------------------
# Checkpoint v4: per-window sketch planes (ISSUE 8). The planes must
# round-trip BIT-EXACT through a KillPoint at mid-window (open windows'
# partial sketch state resumes, not restarts), single-chip AND sharded;
# v2/v3 files must still load — sketch planes re-initialize with a loud
# log, never a crash.

from deepflow_tpu.aggregator.sketchplane import SketchConfig  # noqa: E402
from deepflow_tpu.aggregator.window import WindowManager  # noqa: E402
from deepflow_tpu.ops.histogram import LogHistSpec  # noqa: E402

_SK = SketchConfig(
    num_groups=4, hll_precision=7, cms_depth=2, cms_width=256,
    hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
    topk_rows=2, topk_cols=64, pending=8,
)


def _sk_doc_batch(seed, n, t):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 150, n).astype(np.uint32)
    tags = np.zeros((TAG_SCHEMA.num_fields, n), np.uint32)
    tags[TAG_SCHEMA.index("ip0_w3")] = keys
    tags[TAG_SCHEMA.index("server_port")] = 443
    tags[TAG_SCHEMA.index("protocol")] = 6
    tags[TAG_SCHEMA.index("l3_epc_id1")] = keys % 5
    meters = np.zeros((FLOW_METER.num_fields, n), np.float32)
    meters[FLOW_METER.index("byte_tx")] = 100.0
    meters[FLOW_METER.index("rtt_sum")] = 10.0
    meters[FLOW_METER.index("rtt_count")] = 1.0
    hi = keys * np.uint32(2654435761) + np.uint32(1)
    lo = keys ^ np.uint32(0x9E3779B9)
    return (np.full(n, t, np.uint32), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(tags), jnp.asarray(meters), np.ones(n, bool))


_SK_TIMES = (T0, T0 + 1, T0 + 2, T0 + 5, T0 + 6)
_SK_KILL_AFTER = 2  # mid-window: T0+2 ingested, its window still open


def _assert_blocks_equal(a, b):
    assert a.window == b.window and a.n_updates == b.n_updates
    for lane in ("hll", "cms", "hist", "tk_votes", "tk_hi", "tk_lo",
                 "tk_ida", "tk_idb"):
        np.testing.assert_array_equal(
            getattr(a, lane), getattr(b, lane), err_msg=(a.window, lane)
        )


def _flush_stream_equal(got, want):
    assert [f.window_idx for f in got] == [f.window_idx for f in want]
    for g, w in zip(got, want):
        assert g.count == w.count
        np.testing.assert_array_equal(g.key_hi, w.key_hi)
        np.testing.assert_array_equal(g.key_lo, w.key_lo)
        assert (g.sketches is None) == (w.sketches is None)
        if g.sketches is not None:
            _assert_blocks_equal(g.sketches, w.sketches)


def test_sketch_planes_roundtrip_killpoint_mid_window_single_chip(tmp_path):
    def batches():
        return [_sk_doc_batch(60 + i, 96, t) for i, t in enumerate(_SK_TIMES)]

    # uninterrupted oracle
    oracle = WindowManager(WindowConfig(capacity=1 << 11, sketch=_SK))
    want = []
    for b in batches():
        want.extend(oracle.ingest(*b))
    want.extend(oracle.flush_all())

    # victim: killed mid-window right after the checkpoint barrier
    path = tmp_path / "sk.ckpt"
    victim = WindowManager(WindowConfig(capacity=1 << 11, sketch=_SK))
    got = []
    with pytest.raises(chaos.KillPoint):
        for i, b in enumerate(batches()):
            got.extend(victim.ingest(*b))
            if i == _SK_KILL_AFTER:
                got.extend(save_window_state(victim, path))
                raise chaos.KillPoint("process death mid-window")

    recovered = load_window_state(path, TAG_SCHEMA, FLOW_METER)
    assert recovered.sk is not None
    # the plane itself round-trips bit-exact
    for lane in ("win", "count", "hll", "cms", "hist", "tk_votes", "tk_hi"):
        np.testing.assert_array_equal(
            np.asarray(getattr(recovered.sk, lane)),
            np.asarray(getattr(victim.sk, lane)), err_msg=lane,
        )
    # ...and the continued run is indistinguishable from the oracle,
    # flushed rows AND closed sketch blocks
    for b in batches()[_SK_KILL_AFTER + 1 :]:
        got.extend(recovered.ingest(*b))
    got.extend(recovered.flush_all())
    _flush_stream_equal(got, want)
    assert recovered.get_counters()["sketch_rows"] == (
        oracle.get_counters()["sketch_rows"]
    )


def test_sketch_planes_roundtrip_killpoint_mid_window_sharded(tmp_path):
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=8, hll_precision=7,
        cms_depth=2, cms_width=256,
        hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
        topk_cols=64, sketch_pending=8,
    )
    gen = SyntheticFlowGen(num_tuples=200, seed=61)
    batches = [gen.flow_batch(128, t) for t in _SK_TIMES]

    def run(wm, bs):
        out, blocks = [], []
        for fb in bs:
            out.extend(wm.ingest(fb.tags, fb.meters, fb.valid))
            blocks.extend(wm.pop_closed_sketches())
        out.extend(wm.drain())
        blocks.extend(wm.pop_closed_sketches())
        return out, blocks

    oracle = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    want_docs, want_blocks = run(oracle, batches)

    path = tmp_path / "sk_sharded.ckpt"
    victim = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    got_docs, got_blocks = [], []
    with pytest.raises(chaos.KillPoint):
        for i, fb in enumerate(batches):
            got_docs.extend(victim.ingest(fb.tags, fb.meters, fb.valid))
            got_blocks.extend(victim.pop_closed_sketches())
            if i == _SK_KILL_AFTER:
                save_sharded_state(victim, path)
                raise chaos.KillPoint("process death mid-window")

    recovered = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    restore_sharded_state(recovered, path)
    for lane in ("win", "count", "hll", "cms", "tk_votes", "tk_hi"):
        np.testing.assert_array_equal(
            np.asarray(getattr(recovered.sketches, lane)),
            np.asarray(getattr(victim.sketches, lane)), err_msg=lane,
        )
    d2, b2 = run(recovered, batches[_SK_KILL_AFTER + 1 :])
    got_docs.extend(d2)
    got_blocks.extend(b2)
    assert [d.size for d in got_docs] == [d.size for d in want_docs]
    assert [b.window for b in got_blocks] == [b.window for b in want_blocks]
    for g, w in zip(got_blocks, want_blocks):
        _assert_blocks_equal(g, w)


def test_pre_v4_checkpoints_reinit_sketch_planes_loudly(tmp_path, caplog):
    """v3-era files (no sk_* arrays) must LOAD: the sketch tier
    re-initializes with a loud log — resuming an exact-only snapshot
    into a sketch-enabled deployment is a degradation, not a crash."""
    import logging

    from deepflow_tpu.aggregator import checkpoint as ckpt_mod

    wm = WindowManager(WindowConfig(capacity=1 << 10, sketch=_SK))
    list(wm.ingest(*_sk_doc_batch(62, 64, T0)))
    path = tmp_path / "v3.ckpt"
    save_window_state(wm, path)
    # strip the file back to a v3 layout: no sketch arrays, no sketch meta
    meta, arrays = ckpt_mod._read_checkpoint(path)
    meta = {k: v for k, v in meta.items()
            if not k.startswith("sketch") and k != "digest"}
    meta["version"] = 3
    arrays = {k: v for k, v in arrays.items() if not k.startswith("sk_")}
    ckpt_mod._write_checkpoint(path, meta, arrays)

    with caplog.at_level(logging.WARNING):
        restored = load_window_state(
            path, TAG_SCHEMA, FLOW_METER, sketch_config=_SK
        )
    assert any("no sketch planes" in r.message for r in caplog.records)
    assert restored.sk is not None
    assert int(np.asarray(restored.sk.rows)) == 0  # fresh plane
    # exact state still restored
    assert restored.start_window == wm.start_window
    # and the manager keeps working with the fresh plane
    flushed = list(restored.ingest(*_sk_doc_batch(63, 64, T0 + 5)))
    flushed += restored.flush_all()
    assert any(f.sketches is not None for f in flushed)


def test_pre_v4_sharded_checkpoint_reinits_sketch_planes_loudly(tmp_path, caplog):
    import logging

    from deepflow_tpu.aggregator import checkpoint as ckpt_mod
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 9, num_services=8, hll_precision=6,
        cms_depth=2, cms_width=128,
        hist=LogHistSpec(bins=16, vmin=1.0, gamma=1.5),
        topk_cols=64, sketch_pending=8,
    )
    wm = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    gen = SyntheticFlowGen(num_tuples=100, seed=64)
    fb = gen.flow_batch(64, T0)
    wm.ingest(fb.tags, fb.meters, fb.valid)
    path = tmp_path / "v3_sharded.ckpt"
    save_sharded_state(wm, path)
    meta, arrays = ckpt_mod._read_checkpoint(path)
    meta = {k: v for k, v in meta.items()
            if not k.startswith("sketch") and k != "digest"}
    meta["version"] = 3
    arrays = {k: v for k, v in arrays.items() if not k.startswith("sk_")}
    ckpt_mod._write_checkpoint(path, meta, arrays)

    fresh = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    with caplog.at_level(logging.WARNING):
        restore_sharded_state(fresh, path)
    assert any("no sketch planes" in r.message for r in caplog.records)
    assert int(np.asarray(fresh.sketches.rows).sum()) == 0
    # exact state restored; the manager keeps working
    fb2 = gen.flow_batch(64, T0 + 5)
    fresh.ingest(fb2.tags, fb2.meters, fb2.valid)
    fresh.drain()
    assert fresh.pop_closed_sketches()


# ---------------------------------------------------------------------------
# Checkpoint v5: rollup-cascade tier state (ISSUE 9). A KillPoint
# mid-minute — after some 1s closes folded into the OPEN 1m tier,
# before it closes — must round-trip bit-exact vs an uninterrupted
# oracle: tier stashes, watermarks, device counter lanes AND the open
# parents' partially-merged sketch blocks. v4-and-earlier files load
# with the tiers re-initialized + a loud log.

from deepflow_tpu.aggregator.cascade import CascadeConfig  # noqa: E402

_CASC_TIMES = (T0, T0 + 5, T0 + 10, T0 + 45, T0 + 100)
_CASC_KILL_AFTER = 2  # T0+10 ingested: seconds < T0+8 folded, minute open


def _casc_cfg():
    return WindowConfig(
        capacity=1 << 11, sketch=_SK,
        cascade=CascadeConfig(intervals=(60,), capacity=1 << 11),
    )


def _tier_stream_equal(got, want):
    assert [f.window_idx for f in got] == [f.window_idx for f in want]
    for g, w in zip(got, want):
        assert (g.tier, g.interval, g.count) == (w.tier, w.interval, w.count)
        np.testing.assert_array_equal(g.key_hi, w.key_hi)
        np.testing.assert_array_equal(g.key_lo, w.key_lo)
        np.testing.assert_array_equal(g.tags, w.tags)
        np.testing.assert_array_equal(
            g.meters.view(np.uint32), w.meters.view(np.uint32)
        )
        assert (g.sketches is None) == (w.sketches is None)
        if g.sketches is not None:
            _assert_blocks_equal(g.sketches, w.sketches)


def test_cascade_tiers_roundtrip_killpoint_mid_minute(tmp_path):
    def batches():
        return [_sk_doc_batch(70 + i, 96, t) for i, t in enumerate(_CASC_TIMES)]

    oracle = WindowManager(_casc_cfg())
    want = []
    for b in batches():
        want.extend(oracle.ingest(*b))
    want.extend(oracle.flush_all())
    want_tiers = oracle.pop_tier_windows()
    assert want_tiers, "stream crosses a minute boundary — tiers must close"

    path = tmp_path / "casc.ckpt"
    victim = WindowManager(_casc_cfg())
    got, got_tiers = [], []
    with pytest.raises(chaos.KillPoint):
        for i, b in enumerate(batches()):
            got.extend(victim.ingest(*b))
            got_tiers.extend(victim.pop_tier_windows())
            if i == _CASC_KILL_AFTER:
                # mid-minute: the open 1m tier already holds folded 1s
                # windows and a partially-merged parent sketch block
                assert victim.cascade.pending_blocks[0], "no partial merge"
                got.extend(save_window_state(victim, path))
                raise chaos.KillPoint("process death mid-minute")

    recovered = load_window_state(path, TAG_SCHEMA, FLOW_METER)
    assert recovered.cascade is not None
    # tier stash + lanes round-trip bit-exact
    for lane in ("slot", "key_hi", "key_lo", "tags", "meters", "valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(recovered.cascade.tiers[0], lane)),
            np.asarray(getattr(victim.cascade.tiers[0], lane)), err_msg=lane,
        )
    np.testing.assert_array_equal(
        np.asarray(recovered.cascade.lanes_dev),
        np.asarray(victim.cascade.lanes_dev),
    )
    assert recovered.cascade.watermarks == victim.cascade.watermarks
    assert sorted(recovered.cascade.pending_blocks[0]) == sorted(
        victim.cascade.pending_blocks[0]
    )
    # the continued run is indistinguishable from the oracle: 1s stream
    # AND the closed tier windows (rows, meters bits, merged blocks)
    for b in batches()[_CASC_KILL_AFTER + 1 :]:
        got.extend(recovered.ingest(*b))
        got_tiers.extend(recovered.pop_tier_windows())
    got.extend(recovered.flush_all())
    got_tiers.extend(recovered.pop_tier_windows())
    _flush_stream_equal(got, want)
    _tier_stream_equal(got_tiers, want_tiers)
    assert recovered.get_counters()["cascade_rows"] == (
        oracle.get_counters()["cascade_rows"]
    )


def test_cascade_tiers_roundtrip_killpoint_sharded(tmp_path):
    from deepflow_tpu.parallel.mesh import make_mesh
    from deepflow_tpu.parallel.sharded import (
        ShardedConfig,
        ShardedPipeline,
        ShardedWindowManager,
    )

    cfg = ShardedConfig(
        capacity_per_device=1 << 10, num_services=8, hll_precision=7,
        cms_depth=2, cms_width=256,
        hist=LogHistSpec(bins=32, vmin=1.0, gamma=1.3),
        topk_cols=64, sketch_pending=8,
        cascade=(60,), cascade_capacity=1 << 10,
    )
    gen = SyntheticFlowGen(num_tuples=150, seed=71)
    batches = [gen.flow_batch(128, t) for t in _CASC_TIMES]

    def run(wm, bs):
        docs, tiers = [], []
        for fb in bs:
            docs.extend(wm.ingest(fb.tags, fb.meters, fb.valid))
            tiers.extend(wm.pop_tier_docbatches())
        docs.extend(wm.drain())
        tiers.extend(wm.pop_tier_docbatches())
        return docs, tiers

    oracle = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    want_docs, want_tiers = run(oracle, batches)
    assert want_tiers

    path = tmp_path / "casc_sharded.ckpt"
    victim = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    got_docs, got_tiers = [], []
    with pytest.raises(chaos.KillPoint):
        for i, fb in enumerate(batches):
            got_docs.extend(victim.ingest(fb.tags, fb.meters, fb.valid))
            got_tiers.extend(victim.pop_tier_docbatches())
            if i == _CASC_KILL_AFTER:
                save_sharded_state(victim, path)
                raise chaos.KillPoint("process death mid-minute")

    recovered = ShardedWindowManager(ShardedPipeline(make_mesh(2), cfg))
    restore_sharded_state(recovered, path)
    for lane in ("slot", "key_hi", "key_lo", "tags", "meters", "valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(recovered.tier_stashes[0], lane)),
            np.asarray(getattr(victim.tier_stashes[0], lane)), err_msg=lane,
        )
    d2, t2 = run(recovered, batches[_CASC_KILL_AFTER + 1 :])
    got_docs.extend(d2)
    got_tiers.extend(t2)
    assert [d.size for d in got_docs] == [d.size for d in want_docs]
    assert [(iv, db.size) for iv, db in got_tiers] == [
        (iv, db.size) for iv, db in want_tiers
    ]
    for (gi, g), (wi, w) in zip(got_tiers, want_tiers):
        np.testing.assert_array_equal(g.tags, w.tags)
        np.testing.assert_array_equal(
            g.meters.view(np.uint32), w.meters.view(np.uint32)
        )


def test_pre_v5_checkpoints_reinit_cascade_tiers_loudly(tmp_path, caplog):
    """v4-era files (no casc_* arrays) must LOAD into a cascade-enabled
    deployment: tiers re-initialize with a loud log — never a crash."""
    import logging

    # a v4-era save: same config minus the cascade
    wm = WindowManager(WindowConfig(capacity=1 << 10, sketch=_SK))
    list(wm.ingest(*_sk_doc_batch(72, 64, T0)))
    path = tmp_path / "v4.ckpt"
    save_window_state(wm, path)

    with caplog.at_level(logging.WARNING):
        restored = load_window_state(
            path, TAG_SCHEMA, FLOW_METER,
            cascade_config=CascadeConfig(intervals=(60,), capacity=1 << 10),
        )
    assert any("no cascade tier state" in r.message for r in caplog.records)
    assert restored.cascade is not None
    assert restored.cascade.watermarks == [0]
    # exact state restored; the cascade works from here on
    restored.ingest(*_sk_doc_batch(73, 64, T0 + 100))
    restored.flush_all()
    assert restored.pop_tier_windows()


# ---------------------------------------------------------------------------
# Multi-host mesh (ISSUE 14): a REAL 2-process `jax.distributed` run
# where one process is killed mid-stream (os._exit after a checkpoint
# barrier) and recovers COORDINATION-FREE — restore its per-host
# sharded checkpoint, replay its OWN journal (filenames carry the
# process index), continue — pinned bit-exact vs the uninterrupted
# single-process oracle. The subprocess results are memoized in
# tests/mesh_harness.py and shared with test_mesh_multiproc/
# test_perf_gate.


def test_two_process_kill_one_host_recovers_from_local_journal():
    import mesh_harness as mh

    kill = mh.mesh2_kill_result()
    oracle = mh.oracle_result()

    # the surviving host (process 0) is untouched by its peer's death:
    # its stream stays bit-exact (the data path never crossed hosts)
    for g, rec in kill["p0"]["groups"].items():
        want = oracle["groups"][g]
        assert rec["stream"] == want["stream"]
        assert rec["counters"] == want["counters"]

    # the killed host: outputs up to the checkpoint barrier survived
    # delivery; post-barrier outputs died with the process and the
    # journal replay re-creates them — the combined stream is the
    # uninterrupted oracle's, row for row
    (g1,) = kill["p1_gen1"]["groups"].keys()
    gen1 = kill["p1_gen1"]["groups"][g1]
    gen2 = kill["p1_gen2"]["groups"][g1]
    want = oracle["groups"][g1]
    assert gen1["ckpt_stream_len"] is not None
    combined = gen1["stream"][: gen1["ckpt_stream_len"]] + gen2["stream"]
    assert combined == want["stream"]
    combined_blocks = (
        gen1["blocks"][: gen1["ckpt_blocks_len"]] + gen2["blocks"]
    )
    assert combined_blocks == want["blocks"]

    # counter conservation across the death: restored totals + replayed
    # + post-recovery ingest land exactly on the oracle's counter block
    # (sketch_blocks_closed is a host int outside the snapshot — its
    # conservation is the combined blocks pin above)
    for k in ("flow_in", "flushed_doc", "drop_before_window",
              "window_advances"):
        assert gen2["counters"][k] == want["counters"][k], k
