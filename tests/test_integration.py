"""Integration plane tests: format decoders, HTTP collector → server
ingesters over real sockets, dfstats self-telemetry loop, PromQL subset,
flame graphs."""

from __future__ import annotations

import gzip
import time
import urllib.request

import numpy as np
import pytest

from deepflow_tpu.integration.collector import IntegrationCollector
from deepflow_tpu.integration.dfstats import points_to_influx, stats_sink
from deepflow_tpu.integration.formats import (
    PromSeries,
    encode_remote_write,
    parse_influx_lines,
    parse_otlp_traces,
    parse_remote_write,
    parse_folded,
)
from deepflow_tpu.ingest.framing import MessageType
from deepflow_tpu.ingest.receiver import Receiver
from deepflow_tpu.ingest.sender import UniformSender
from deepflow_tpu.querier.profile import flame_tree, query_flame
from deepflow_tpu.querier.promql import PromQLError, query_instant
from deepflow_tpu.server.integration import IntegrationIngester
from deepflow_tpu.storage.store import ColumnarStore
from deepflow_tpu.utils.stats import StatsCollector

T0 = 1_700_000_000


def test_influx_line_parse():
    pts, errors = parse_influx_lines(
        'cpu,host=web-1,az=a usage=0.5,count=3i 1700000000000000000\n'
        'mem,host=web-1 used=12.5\n'
        'bad line without fields\n'
        'esc\\,aped,t=v\\ x f=1i'
    )
    assert errors == 1
    assert pts[0].measurement == "cpu"
    assert pts[0].tags == {"host": "web-1", "az": "a"}
    assert pts[0].fields == {"usage": 0.5, "count": 3.0}
    assert pts[0].timestamp_ns == 1700000000000000000
    assert pts[2].measurement == "esc,aped"
    assert pts[2].tags == {"t": "v x"}


def test_remote_write_roundtrip():
    series = [
        PromSeries({"__name__": "http_requests_total", "job": "api", "code": "200"},
                   [(T0 * 1000, 10.0), ((T0 + 30) * 1000, 25.0)]),
        PromSeries({"__name__": "up", "job": "api"}, [(T0 * 1000, 1.0)]),
    ]
    dec = parse_remote_write(encode_remote_write(series))
    assert len(dec) == 2
    assert dec[0].labels["__name__"] == "http_requests_total"
    assert dec[0].samples == [(T0 * 1000, 10.0), ((T0 + 30) * 1000, 25.0)]


def _otlp_body():
    # build via the generic pb helpers: one resource span with service.name
    from deepflow_tpu.ingest.codec import _put_varint

    def ld(field, payload):
        b = bytearray()
        _put_varint(b, field << 3 | 2)
        _put_varint(b, len(payload))
        b += payload
        return bytes(b)

    def vi(field, v):
        b = bytearray()
        _put_varint(b, field << 3 | 0)
        _put_varint(b, v)
        return bytes(b)

    sname = ld(1, b"service.name") + ld(2, ld(1, b"checkout"))
    resource = ld(1, ld(1, sname))  # ResourceSpans.resource = Resource{attributes}
    span = (
        ld(1, bytes(16))  # trace_id
        + ld(2, bytes.fromhex("00000000000000aa"))
        + ld(5, b"GET /cart")
        + vi(6, 2)  # SPAN_KIND_SERVER
        + vi(7, T0 * 10**9)
        + vi(8, (T0 * 10**9) + 5_000_000)  # 5ms
        + ld(9, ld(1, b"http.method") + ld(2, ld(1, b"GET")))
        + ld(9, ld(1, b"http.status_code") + ld(2, ld(1, b"200")))
        # Status{message="deadline exceeded", code=STATUS_CODE_ERROR}:
        # code is field 3; field 2 is the message string and must be skipped
        + ld(15, ld(2, b"deadline exceeded") + vi(3, 2))
    )
    scope_spans = ld(2, ld(2, span))  # ResourceSpans.scope_spans = ScopeSpans{spans}
    return ld(1, resource + scope_spans)


def test_otlp_parse():
    spans = parse_otlp_traces(_otlp_body())
    assert len(spans) == 1
    s = spans[0]
    assert s.service == "checkout"
    assert s.name == "GET /cart"
    assert s.kind == 2
    assert s.end_us - s.start_us == 5000
    assert s.attributes["http.method"] == "GET"
    assert s.status_code == 2  # STATUS_CODE_ERROR survives a message string


def test_folded_parse_and_flame_tree():
    samples, errors = parse_folded("a;b;c 10\na;b 5\na;b;d 1\nbad\n")
    assert errors == 1
    tree = flame_tree([s.stack for s in samples], [s.value for s in samples])
    assert tree["total_value"] == 16
    a = tree["children"][0]
    assert a["name"] == "a" and a["total_value"] == 16
    b = a["children"][0]
    assert b["self_value"] == 5 and b["total_value"] == 16


@pytest.fixture()
def stack():
    recv = Receiver()
    recv.start()
    store = ColumnarStore()
    ing = IntegrationIngester(recv, store, writer_args={"flush_interval_s": 0.05})
    col = IntegrationCollector([("127.0.0.1", recv.tcp_port)])
    yield recv, store, ing, col
    col.stop()
    ing.stop()
    recv.stop()


def _post(port, path, body, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status
    except urllib.error.HTTPError as e:
        return e.code


def _wait(cond, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_http_collector_to_ingesters_e2e(stack):
    recv, store, ing, col = stack
    # telegraf (gzip), prometheus (identity), profile, otel
    influx = f"cpu,host=h1 usage=0.7 {T0}000000000\ncpu,host=h2 usage=0.2 {T0}000000000"
    assert _post(col.port, "/influxdb/api/v2/write", gzip.compress(influx.encode()),
                 {"Content-Encoding": "gzip"}) == 200
    rw = encode_remote_write(
        [PromSeries({"__name__": "up", "job": "api"}, [(T0 * 1000, 1.0)])]
    )
    assert _post(col.port, "/api/v1/prom/write", rw) == 204
    assert _post(col.port, "/api/v1/prom/write", b"x", {"Content-Encoding": "snappy"}) == 415
    prof = f"svc-a\x00cpu\x00{T0}\nmain;work;hot 90\nmain;idle 10".encode()
    assert _post(col.port, "/api/v1/profile", prof) == 200
    assert _post(col.port, "/v1/traces", _otlp_body()) == 200
    assert _post(col.port, "/nope", b"") == 404

    assert _wait(lambda: ing.get_counters()["rows_written"] >= 2 + 1 + 2 + 1), ing.get_counters()
    ing.flush()

    ext = store.scan("ext_metrics", "metrics")
    assert len(ext["time"]) == 2 and set(ext["field_name"]) == {"usage"}
    prom = store.scan("prometheus", "samples")
    assert prom["metric"][0] == "up" and prom["value"][0] == 1.0
    tree = query_flame(store, app_service="svc-a")
    assert tree["total_value"] == 100
    l7 = store.scan("flow_log", "l7_flow_log", columns=["app_service", "endpoint", "response_duration"])
    assert l7["app_service"][0] == "checkout"
    assert l7["response_duration"][0] == 5000


def test_dfstats_loop(stack):
    recv, store, ing, col = stack
    sc = StatsCollector(interval_s=999)
    sc.register("flow_map", lambda: {"packets_in": 42})
    snd = UniformSender([("127.0.0.1", recv.tcp_port)], MessageType.DFSTATS,
                        agent_id=1, prefer_native_queue=False)
    sc.add_sink(stats_sink(snd))
    sc.tick(now=float(T0))
    assert _wait(lambda: ing.get_counters()["rows_written"] >= 1)
    ing.flush()
    rows = store.scan("deepflow_stats", "stats")
    assert rows["virtual_table"][0] == "flow_map"
    assert rows["value"][0] == 42.0
    snd.close()


def test_points_to_influx_format():
    from deepflow_tpu.utils.stats import StatsPoint

    text = points_to_influx(
        [StatsPoint(float(T0), "writer", (("db", "flow metrics"),), {"rows": 5})]
    )
    # ints keep influx `i` typing; tag values escape, not mangle
    assert text == f"writer,db=flow\\ metrics rows=5i {T0}000000000"
    # ...and the frame decodes back to the original tag value + int
    from deepflow_tpu.integration.formats import parse_influx_lines

    points, errors = parse_influx_lines(text)
    assert errors == 0
    assert points[0].tags == {"db": "flow metrics"}
    assert points[0].fields == {"rows": 5.0}


def test_promql_queries():
    store = ColumnarStore()
    from deepflow_tpu.server.integration import PROM_SCHEMA

    store.create_table("prometheus", PROM_SCHEMA)
    rows = []
    for job, inst, base in (("api", "i1", 100), ("api", "i2", 200), ("db", "i3", 50)):
        for k in range(5):
            rows.append((T0 + 15 * k, "http_total", f"instance={inst},job={job}", base + 10 * k))
    store.insert(
        "prometheus",
        "samples",
        {
            "time": np.asarray([r[0] for r in rows], np.uint32),
            "metric": np.asarray([r[1] for r in rows]),
            "labels": np.asarray([r[2] for r in rows]),
            "value": np.asarray([r[3] for r in rows], np.float64),
        },
    )
    t = T0 + 100
    out = query_instant(store, 'http_total{job="api"}', t)
    assert len(out) == 2 and {o["value"] for o in out} == {140.0, 240.0}
    out = query_instant(store, 'sum by (job) (http_total)', t)
    assert {(o["labels"]["job"], o["value"]) for o in out} == {("api", 380.0), ("db", 90.0)}
    out = query_instant(store, 'sum by (job) (rate(http_total[2m]))', t)
    api = [o for o in out if o["labels"]["job"] == "api"][0]
    assert api["value"] == pytest.approx(2 * (40 / 60))
    with pytest.raises(PromQLError):
        query_instant(store, "rate(http_total)", t)
    with pytest.raises(PromQLError):
        query_instant(store, "sum by job http_total{", t)


def test_promql_rate_counter_reset():
    """A process restart inside the window (counter drops to ~0) must
    yield the reset-adjusted positive rate, not a negative one."""
    store = ColumnarStore()
    from deepflow_tpu.server.integration import PROM_SCHEMA

    store.create_table("prometheus", PROM_SCHEMA)
    # 1000 → 1060 → restart → 5 → 65; increases: 60 + 5 + 60 = 125 over 45s
    times = [T0, T0 + 15, T0 + 30, T0 + 45]
    vals = [1000.0, 1060.0, 5.0, 65.0]
    store.insert(
        "prometheus",
        "samples",
        {
            "time": np.asarray(times, np.uint32),
            "metric": np.asarray(["restarts_total"] * 4),
            "labels": np.asarray(["job=api"] * 4),
            "value": np.asarray(vals, np.float64),
        },
    )
    out = query_instant(store, "rate(restarts_total[2m])", T0 + 50)
    assert len(out) == 1
    assert out[0]["value"] == pytest.approx(125 / 45)


def test_pack_tags_escaping_roundtrip():
    from deepflow_tpu.integration.formats import pack_tags, unpack_tags

    tags = {"url": "/search?a=1,b=2", "k=y": "v\\x", "plain": "ok"}
    assert unpack_tags(pack_tags(tags)) == tags
