// Document protobuf → struct-of-arrays decoder — the ingester's DecodePB
// hot loop (/root/reference/server/libs/app/codec.go:28,
// flow_metrics/unmarshaller/unmarshaller.go:220) as native code.
//
// Wire format: metric.proto Document{timestamp=1, tag=2, meter=3, flags=4}
// (see deepflow_tpu/ingest/codec.py, the Python reference implementation
// this must match byte-for-byte; conformance is pinned by
// tests/test_native.py).
//
// Schema-agnostic by construction: the caller passes
//   * tag_col[slot]   — semantic slot → output tag column (-1 = absent)
//   * meter maps      — (submsg<<5 | field) → meter column, per meter id
//   * a code→code_id table
// so the C++ never hardcodes the Python TAG_SCHEMA layout.

#include <cstdint>
#include <cstring>

namespace {

// Semantic tag slots — ABI shared with deepflow_tpu/native/__init__.py
// (order must match _SLOT_NAMES there).
enum Slot {
  S_CODE_ID = 0,
  S_METER_ID,
  S_GLOBAL_THREAD_ID,
  S_AGENT_ID,
  S_IS_IPV6,
  S_IP0_W0,
  S_IP0_W1,
  S_IP0_W2,
  S_IP0_W3,
  S_IP1_W0,
  S_IP1_W1,
  S_IP1_W2,
  S_IP1_W3,
  S_L3_EPC_ID,
  S_L3_EPC_ID1,
  S_MAC0_HI,
  S_MAC0_LO,
  S_MAC1_HI,
  S_MAC1_LO,
  S_DIRECTION,
  S_TAP_SIDE,
  S_PROTOCOL,
  S_ACL_GID,
  S_SERVER_PORT,
  S_TAP_PORT,
  S_TAP_TYPE,
  S_L7_PROTOCOL,
  S_GPID0,
  S_GPID1,
  S_ENDPOINT_HASH,
  S_BIZ_TYPE,
  S_SIGNAL_SOURCE,
  S_POD_ID,
  NUM_SLOTS,
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  uint64_t varint() {
    uint64_t out = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      out |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return out;
      shift += 7;
      if (shift >= 70) break;
    }
    fail = true;
    return 0;
  }

  // Returns field id; wire type in *wire; for LEN fields sets *sub.
  // Returns 0 at end.
  uint32_t next(uint32_t* wire, Cursor* sub, uint64_t* value) {
    if (p >= end || fail) return 0;
    uint64_t key = varint();
    if (fail) return 0;
    uint32_t field = static_cast<uint32_t>(key >> 3);
    *wire = static_cast<uint32_t>(key & 7);
    switch (*wire) {
      case 0:
        *value = varint();
        break;
      case 2: {
        uint64_t len = varint();
        if (fail || p + len > end) {
          fail = true;
          return 0;
        }
        sub->p = p;
        sub->end = p + len;
        sub->fail = false;
        p += len;
        break;
      }
      case 5:
        if (p + 4 > end) { fail = true; return 0; }
        *value = 0;
        memcpy(value, p, 4);
        p += 4;
        break;
      case 1:
        if (p + 8 > end) { fail = true; return 0; }
        memcpy(value, p, 8);
        p += 8;
        break;
      default:
        fail = true;
        return 0;
    }
    return field;
  }
};

inline uint32_t rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6B;
  h ^= h >> 13;
  h *= 0xC2B2AE35;
  h ^= h >> 16;
  return h;
}

// Identical to deepflow_tpu/ops/hashing._fold(cols, SEED_HI) over the
// little-endian u32 words of the zero-padded string.
uint32_t hash_string(const uint8_t* s, uint32_t len) {
  if (len == 0) return 0;
  uint32_t n_words = (len + 3) / 4;
  uint32_t h = 0x9747B28C;  // SEED_HI
  for (uint32_t i = 0; i < n_words; i++) {
    uint32_t w = 0;
    uint32_t take = len - i * 4 < 4 ? len - i * 4 : 4;
    memcpy(&w, s + i * 4, take);  // little-endian load, zero padded
    uint32_t k = w * 0xCC9E2D51u;
    k = rotl32(k, 15);
    k = k * 0x1B873593u;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xE6546B64u;
  }
  h ^= n_words * 4;
  return fmix32(h);
}

struct DecodeCtx {
  const int32_t* tag_col;
  uint32_t t_cols;
  const int32_t* meter_maps[8];  // by meter_id; (sub<<5|fid) → col
  int32_t meter_sub_field[8];    // Meter.{flow=2,usage=3,app=4}; -1 unknown
  bool meter_flat[8];            // UsageMeter has flat fields
  const uint64_t* codes;
  const uint32_t* code_ids;
  uint32_t n_codes;
};

inline void set_tag(uint32_t* row, const DecodeCtx& ctx, int slot, uint32_t v) {
  int32_t col = ctx.tag_col[slot];
  if (col >= 0) row[col] = v;
}

void decode_ip(uint32_t* row, const DecodeCtx& ctx, Cursor ip, int base_slot) {
  size_t len = ip.end - ip.p;
  if (len == 4) {
    uint32_t v = (uint32_t(ip.p[0]) << 24) | (uint32_t(ip.p[1]) << 16) |
                 (uint32_t(ip.p[2]) << 8) | uint32_t(ip.p[3]);
    set_tag(row, ctx, base_slot + 3, v);
  } else if (len == 16) {
    for (int w = 0; w < 4; w++) {
      const uint8_t* q = ip.p + w * 4;
      uint32_t v = (uint32_t(q[0]) << 24) | (uint32_t(q[1]) << 16) |
                   (uint32_t(q[2]) << 8) | uint32_t(q[3]);
      set_tag(row, ctx, base_slot + w, v);
    }
  }
}

// status codes
enum { OK = 0, ERR_DECODE = 1, ERR_METER = 2 };

int decode_one(const uint8_t* msg, uint32_t len, const DecodeCtx& ctx,
               uint32_t* tag_row, float* meter_row, uint32_t* ts,
               uint32_t* flags, uint8_t* meter_id_out, uint64_t* str_offs,
               uint32_t* str_lens, const uint8_t* base) {
  Cursor doc{msg, msg + len};
  Cursor minitag{nullptr, nullptr}, meter_buf{nullptr, nullptr};
  uint32_t wire;
  uint64_t v;
  Cursor sub{nullptr, nullptr};
  while (uint32_t field = doc.next(&wire, &sub, &v)) {
    switch (field) {
      case 1: *ts = static_cast<uint32_t>(v); break;
      case 2: minitag = sub; break;
      case 3: meter_buf = sub; break;
      case 4: *flags = static_cast<uint32_t>(v); break;
      default: break;
    }
  }
  if (doc.fail) return ERR_DECODE;

  // ---- MiniTag{field=1, code=2} ----
  uint64_t code = 0;
  Cursor minifield{nullptr, nullptr};
  while (uint32_t field = minitag.next(&wire, &sub, &v)) {
    if (field == 1) minifield = sub;
    else if (field == 2) code = v;
  }
  if (minitag.fail) return ERR_DECODE;

  while (uint32_t field = minifield.next(&wire, &sub, &v)) {
    switch (field) {
      case 1: decode_ip(tag_row, ctx, sub, S_IP0_W0); break;
      case 2: decode_ip(tag_row, ctx, sub, S_IP1_W0); break;
      case 3: set_tag(tag_row, ctx, S_GLOBAL_THREAD_ID, v); break;
      case 4: set_tag(tag_row, ctx, S_IS_IPV6, v); break;
      case 5:
      case 6: {
        int64_t iv = static_cast<int64_t>(v);
        set_tag(tag_row, ctx, field == 5 ? S_L3_EPC_ID : S_L3_EPC_ID1,
                static_cast<uint32_t>(iv & 0xFFFF));
        break;
      }
      case 7:
        set_tag(tag_row, ctx, S_MAC0_HI, v >> 32);
        set_tag(tag_row, ctx, S_MAC0_LO, v & 0xFFFFFFFF);
        break;
      case 8:
        set_tag(tag_row, ctx, S_MAC1_HI, v >> 32);
        set_tag(tag_row, ctx, S_MAC1_LO, v & 0xFFFFFFFF);
        break;
      case 9: set_tag(tag_row, ctx, S_DIRECTION, v); break;
      case 10: set_tag(tag_row, ctx, S_TAP_SIDE, v); break;
      case 11: set_tag(tag_row, ctx, S_PROTOCOL, v); break;
      case 12: set_tag(tag_row, ctx, S_ACL_GID, v); break;
      case 13: set_tag(tag_row, ctx, S_SERVER_PORT, v); break;
      case 14: set_tag(tag_row, ctx, S_AGENT_ID, v); break;
      case 15: set_tag(tag_row, ctx, S_TAP_PORT, v); break;
      case 16: set_tag(tag_row, ctx, S_TAP_TYPE, v); break;
      case 17: set_tag(tag_row, ctx, S_L7_PROTOCOL, v); break;
      case 20: set_tag(tag_row, ctx, S_GPID0, v); break;
      case 21: set_tag(tag_row, ctx, S_GPID1, v); break;
      case 22: set_tag(tag_row, ctx, S_SIGNAL_SOURCE, v); break;
      case 23:
      case 24:
      case 25: {
        int idx = field - 23;
        str_offs[idx] = sub.p - base;
        str_lens[idx] = static_cast<uint32_t>(sub.end - sub.p);
        if (field == 25)
          set_tag(tag_row, ctx, S_ENDPOINT_HASH,
                  hash_string(sub.p, str_lens[idx]));
        break;
      }
      case 27: set_tag(tag_row, ctx, S_POD_ID, v); break;
      case 28: set_tag(tag_row, ctx, S_BIZ_TYPE, v); break;
      default: break;
    }
  }
  if (minifield.fail) return ERR_DECODE;

  // code → dense code_id (linear scan; the table has ~10 entries)
  uint32_t code_id = 0;
  for (uint32_t i = 0; i < ctx.n_codes; i++) {
    if (ctx.codes[i] == code) {
      code_id = ctx.code_ids[i];
      break;
    }
  }
  set_tag(tag_row, ctx, S_CODE_ID, code_id);

  // ---- Meter{meter_id=1, flow=2, usage=3, app=4} ----
  // Mirror the Python decoder: pick the submessage matching the declared
  // meter_id; a missing submessage means an all-zero meter, submessages
  // of other meter kinds are ignored.
  uint32_t meter_id = 0;
  Cursor sub_bufs[8] = {};
  while (uint32_t field = meter_buf.next(&wire, &sub, &v)) {
    if (field == 1) meter_id = static_cast<uint32_t>(v);
    else if (wire == 2 && field < 8) sub_bufs[field] = sub;
  }
  if (meter_buf.fail) return ERR_DECODE;
  if (meter_id >= 8 || ctx.meter_maps[meter_id] == nullptr) return ERR_METER;
  Cursor inner = sub_bufs[ctx.meter_sub_field[meter_id]];
  set_tag(tag_row, ctx, S_METER_ID, meter_id);
  *meter_id_out = static_cast<uint8_t>(meter_id);

  const int32_t* mmap = ctx.meter_maps[meter_id];
  if (ctx.meter_flat[meter_id]) {
    while (uint32_t fid = inner.next(&wire, &sub, &v)) {
      if (wire != 0 || fid >= 32) continue;
      int32_t col = mmap[fid];  // sub 0 → plain fid index
      if (col >= 0) meter_row[col] = static_cast<float>(v);
    }
    if (inner.fail) return ERR_DECODE;
  } else {
    Cursor subm{nullptr, nullptr};
    while (uint32_t smsg = inner.next(&wire, &subm, &v)) {
      if (wire != 2 || smsg >= 8) continue;
      while (uint32_t fid = subm.next(&wire, &sub, &v)) {
        if (wire != 0 || fid >= 32) continue;
        int32_t col = mmap[(smsg << 5) | fid];
        if (col >= 0) meter_row[col] = static_cast<float>(v);
      }
      if (subm.fail) return ERR_DECODE;
    }
    if (inner.fail) return ERR_DECODE;
  }
  return OK;
}

}  // namespace

extern "C" {

// Split a frame body into [len u32 LE][msg] messages; writes offsets (into
// body) and lengths. Returns message count, or -1 on malformed body.
int32_t df_split_messages(const uint8_t* body, uint32_t len, uint64_t* offs,
                          uint32_t* lens, uint32_t max_msgs) {
  uint32_t off = 0;
  uint32_t n = 0;
  while (off + 4 <= len && n < max_msgs) {
    uint32_t size;
    memcpy(&size, body + off, 4);
    off += 4;
    if (off + size > len) return -1;
    offs[n] = off;
    lens[n] = size;
    off += size;
    n++;
  }
  if (off != len) return -1;
  return static_cast<int32_t>(n);
}

// Decode n Documents (concatenated in `buf` at offs/lens) into SoA outputs.
// All outputs are preallocated by the caller with n rows. Returns the
// number of OK rows (status[i]==0).
int32_t df_decode_documents(
    const uint8_t* buf, const uint64_t* offs, const uint32_t* lens, uint32_t n,
    const int32_t* tag_col, uint32_t t_cols,
    const int32_t* flow_map, const int32_t* usage_map, const int32_t* app_map,
    const uint64_t* codes, const uint32_t* code_ids, uint32_t n_codes,
    uint32_t m_cols,  // meters row stride (max over meter schemas)
    uint32_t* tags, float* meters, uint32_t* timestamps, uint32_t* flags,
    uint8_t* meter_ids, uint64_t* str_offs, uint32_t* str_lens,
    uint8_t* status) {
  DecodeCtx ctx{};
  ctx.tag_col = tag_col;
  ctx.t_cols = t_cols;
  for (int i = 0; i < 8; i++) {
    ctx.meter_maps[i] = nullptr;
    ctx.meter_sub_field[i] = -1;
    ctx.meter_flat[i] = false;
  }
  // MeterId::{FLOW=1, USAGE=4, APP=5} → Meter.{flow=2, usage=3, app=4}
  ctx.meter_maps[1] = flow_map;
  ctx.meter_sub_field[1] = 2;
  ctx.meter_maps[4] = usage_map;
  ctx.meter_sub_field[4] = 3;
  ctx.meter_flat[4] = true;
  ctx.meter_maps[5] = app_map;
  ctx.meter_sub_field[5] = 4;
  ctx.codes = codes;
  ctx.code_ids = code_ids;
  ctx.n_codes = n_codes;

  int32_t ok = 0;
  for (uint32_t i = 0; i < n; i++) {
    uint32_t* tag_row = tags + static_cast<size_t>(i) * t_cols;
    float* meter_row = meters + static_cast<size_t>(i) * m_cols;
    int st = decode_one(buf + offs[i], lens[i], ctx, tag_row, meter_row,
                        timestamps + i, flags + i, meter_ids + i,
                        str_offs + static_cast<size_t>(i) * 3,
                        str_lens + static_cast<size_t>(i) * 3, buf);
    status[i] = static_cast<uint8_t>(st);
    if (st == OK) ok++;
  }
  return ok;
}

}  // extern "C"
