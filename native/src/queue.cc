// OverwriteQueue — bounded MPMC byte-blob ring that sheds OLDEST data on
// overflow, with blocking batched reads.
//
// Semantics mirror the reference ingester's queue
// (/root/reference/server/libs/queue/queue.go:43-260): fixed power-of-two
// capacity; Put overwrites the oldest unread item when full (the
// backpressure stance of a telemetry pipeline: drop history, keep now);
// Gets blocks until at least one item is ready, then drains up to `max`.
// Overwritten items are counted (queue.go:139 releases + counter).
//
// The C ABI below is consumed by ctypes (deepflow_tpu/native/__init__.py).
// Items are owned copies: Put memcpys in, Get hands out a malloc'd blob
// the caller frees via dfq_free_blob.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Blob {
  uint8_t* data = nullptr;
  uint32_t len = 0;
};

struct Queue {
  std::vector<Blob> ring;
  size_t head = 0;  // next read
  size_t tail = 0;  // next write
  size_t count = 0;
  uint64_t overwritten = 0;
  uint64_t total_in = 0;
  bool closed = false;
  std::mutex mu;
  std::condition_variable cv;

  explicit Queue(size_t cap) : ring(cap) {}
};

}  // namespace

extern "C" {

void* dfq_new(uint32_t capacity) {
  if (capacity == 0) capacity = 1;
  return new Queue(capacity);
}

void dfq_destroy(void* q_) {
  Queue* q = static_cast<Queue*>(q_);
  for (auto& b : q->ring) free(b.data);
  delete q;
}

// Copy `len` bytes in. Overwrites the oldest unread item when full.
void dfq_put(void* q_, const uint8_t* data, uint32_t len) {
  Queue* q = static_cast<Queue*>(q_);
  uint8_t* copy = static_cast<uint8_t*>(malloc(len ? len : 1));
  memcpy(copy, data, len);
  {
    std::lock_guard<std::mutex> lock(q->mu);
    Blob& slot = q->ring[q->tail];
    if (q->count == q->ring.size()) {
      // full: advance head over the oldest (it lives in `slot`)
      free(slot.data);
      q->head = (q->head + 1) % q->ring.size();
      q->count--;
      q->overwritten++;
    }
    slot.data = copy;
    slot.len = len;
    q->tail = (q->tail + 1) % q->ring.size();
    q->count++;
    q->total_in++;
  }
  q->cv.notify_one();
}

// Blocking batched read: waits up to timeout_ms for >=1 item, then drains
// up to `max`. Returns number of items written to out_data/out_len.
// Caller must dfq_free_blob each returned pointer.
uint32_t dfq_gets(void* q_, uint8_t** out_data, uint32_t* out_len, uint32_t max,
                  int32_t timeout_ms) {
  Queue* q = static_cast<Queue*>(q_);
  std::unique_lock<std::mutex> lock(q->mu);
  if (q->count == 0 && !q->closed) {
    if (timeout_ms < 0) {
      q->cv.wait(lock, [&] { return q->count > 0 || q->closed; });
    } else {
      q->cv.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                     [&] { return q->count > 0 || q->closed; });
    }
  }
  uint32_t n = 0;
  while (n < max && q->count > 0) {
    Blob& slot = q->ring[q->head];
    out_data[n] = slot.data;
    out_len[n] = slot.len;
    slot.data = nullptr;
    slot.len = 0;
    q->head = (q->head + 1) % q->ring.size();
    q->count--;
    n++;
  }
  return n;
}

void dfq_free_blob(uint8_t* data) { free(data); }

void dfq_close(void* q_) {
  Queue* q = static_cast<Queue*>(q_);
  {
    std::lock_guard<std::mutex> lock(q->mu);
    q->closed = true;
  }
  q->cv.notify_all();
}

uint64_t dfq_overwritten(void* q_) {
  Queue* q = static_cast<Queue*>(q_);
  std::lock_guard<std::mutex> lock(q->mu);
  return q->overwritten;
}

uint32_t dfq_len(void* q_) {
  Queue* q = static_cast<Queue*>(q_);
  std::lock_guard<std::mutex> lock(q->mu);
  return static_cast<uint32_t>(q->count);
}

}  // extern "C"
