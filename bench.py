#!/usr/bin/env python
"""Benchmark: flow-records/sec/chip through the L4 rollup hot path.

Measures the steady-state ingest cycle on the attached accelerator,
replaying the BASELINE config-1 workload shape: synthetic
accumulated-flow batches over 10k unique 5-tuples at 1s windows.

The cycle is the production cadence (aggregator/pipeline.py): per batch
one `append` (batch-local groupby pre-reduce → fanout → fingerprint →
accumulator write), and every ACCUM_BATCHES batches one `fold` (the
amortized sort+segment reduce of [stash + accumulator] rows). The
pre-reduce (PERF.md §7) collapses each batch to its unique raw keys
BEFORE the 4-lane doc fanout — exact for any workload, and the reason
fold rows stop scaling with the dup factor. Reported records/sec
includes the full amortized cost of aggregation, not just the append.

Timing uses an explicit host fetch as the sync point: on the remote
accelerator tunnel `block_until_ready` returns before execution
completes (PERF.md §6), so the loop chains state through K cycles and
subtracts one measured fetch latency.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is against the north-star target of 50M records/sec/chip
(BASELINE.json; the reference publishes no absolute numbers — SURVEY §6).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig
from deepflow_tpu.aggregator.pipeline import make_ingest_step
from deepflow_tpu.aggregator.stash import accum_init, stash_init
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ingest.replay import SyntheticFlowGen

TARGET = 50e6  # records/sec/chip north star

# Measured-safe shapes (PERF.md §7, 2026-07-30 on-chip): compile+first
# ~105 s at these sizes, steady 21.3 M rec/s at the 2M batch.
# The fold sorts CAPACITY + ACCUM_BATCHES×4×UNIQUE_CAP rows (262k here);
# the appends sort BATCH raw rows. UNIQUE_CAP bounds per-batch unique
# keys (3x headroom over the 10k-tuple workload); overflow is shed and
# counted, never silent.
BATCH = int(os.environ.get("BENCH_BATCH", 1 << 21))  # flows per step
CAPACITY = int(os.environ.get("BENCH_CAPACITY", 1 << 16))  # stash segments
ACCUM_BATCHES = int(os.environ.get("BENCH_ACCUM_BATCHES", 2))
UNIQUE_CAP = int(os.environ.get("BENCH_UNIQUE_CAP", 1 << 15))
WARMUP_CYCLES = 1
CYCLES = int(os.environ.get("BENCH_CYCLES", 8))


def main():
    gen = SyntheticFlowGen(num_tuples=10_000, seed=0)
    fb = gen.flow_batch(BATCH, 1_700_000_000)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    meters = jnp.asarray(fb.meters)
    valid = jnp.asarray(fb.valid)

    append_fn, fold_fn = make_ingest_step(
        FanoutConfig(), interval=1, batch_unique_cap=UNIQUE_CAP or None
    )
    append = jax.jit(append_fn, donate_argnums=(0, 1))
    fold = jax.jit(fold_fn, donate_argnums=(0, 1))

    stride = FANOUT_LANES * (UNIQUE_CAP or BATCH)
    state = stash_init(CAPACITY, TAG_SCHEMA, FLOW_METER)
    acc = accum_init(ACCUM_BATCHES * stride, TAG_SCHEMA, FLOW_METER)

    def cycle(state, acc):
        for k in range(ACCUM_BATCHES):
            state, acc = append(state, acc, jnp.int32(k * stride), tags, meters, valid)
        return fold(state, acc)

    for _ in range(WARMUP_CYCLES):
        state, acc = cycle(state, acc)
    _ = np.asarray(state.slot[:1])  # true host sync (compile + warmup done)

    t0 = time.perf_counter()
    _ = np.asarray(state.slot[:1])
    fetch_base = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(CYCLES):
        state, acc = cycle(state, acc)
    _ = np.asarray(state.slot[:1])
    dt = time.perf_counter() - t0 - fetch_base

    rate = BATCH * ACCUM_BATCHES * CYCLES / dt
    print(
        json.dumps(
            {
                "metric": "flow_records_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": "records/s",
                "vs_baseline": round(rate / TARGET, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
