#!/usr/bin/env python
"""Benchmark: flow-records/sec/chip through the L4 rollup hot path.

Measures the steady-state ingest cycle on the attached accelerator,
replaying the BASELINE config-1 workload shape: synthetic
accumulated-flow batches over 10k unique 5-tuples at 1s windows.

The cycle is the production cadence (aggregator/pipeline.py): per batch
one `append` (fanout → fingerprint → accumulator write), and every
ACCUM_BATCHES batches one `fold` (the amortized sort+segment reduce of
[stash + accumulator] rows — see PERF.md for why this shape wins on
TPU). Reported records/sec therefore includes the full amortized cost
of aggregation, not just the append.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is against the north-star target of 50M records/sec/chip
(BASELINE.json; the reference publishes no absolute numbers — SURVEY §6).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig
from deepflow_tpu.aggregator.pipeline import make_ingest_step
from deepflow_tpu.aggregator.stash import accum_init, stash_init
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ingest.replay import SyntheticFlowGen

TARGET = 50e6  # records/sec/chip north star

# Shape ceiling: the fold sorts CAPACITY + ACCUM_BATCHES×4×BATCH rows.
# Remote compiles at ≥~500k rows have taken >550 s and once wedged the
# accelerator tunnel for hours (PERF.md §5), so the default fold stays
# ≤ ~200k rows — a measured-safe compile (~35 s at 131k). Larger, faster
# amortizations can be opted into per-run: BENCH_ACCUM_BATCHES=8 etc.
BATCH = int(os.environ.get("BENCH_BATCH", 1 << 14))  # flows per step
CAPACITY = int(os.environ.get("BENCH_CAPACITY", 1 << 16))  # stash segments
ACCUM_BATCHES = int(os.environ.get("BENCH_ACCUM_BATCHES", 2))
WARMUP_CYCLES = 1
CYCLES = int(os.environ.get("BENCH_CYCLES", 8))


def main():
    gen = SyntheticFlowGen(num_tuples=10_000, seed=0)
    fb = gen.flow_batch(BATCH, 1_700_000_000)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    meters = jnp.asarray(fb.meters)
    valid = jnp.asarray(fb.valid)

    append_fn, fold_fn = make_ingest_step(FanoutConfig(), interval=1)
    append = jax.jit(append_fn, donate_argnums=(0, 1))
    fold = jax.jit(fold_fn, donate_argnums=(0, 1))

    doc_rows = FANOUT_LANES * BATCH
    state = stash_init(CAPACITY, TAG_SCHEMA, FLOW_METER)
    acc = accum_init(ACCUM_BATCHES * doc_rows, TAG_SCHEMA, FLOW_METER)

    def cycle(state, acc):
        for k in range(ACCUM_BATCHES):
            state, acc = append(state, acc, jnp.int32(k * doc_rows), tags, meters, valid)
        return fold(state, acc)

    for _ in range(WARMUP_CYCLES):
        state, acc = cycle(state, acc)
    jax.block_until_ready((state, acc))

    t0 = time.perf_counter()
    for _ in range(CYCLES):
        state, acc = cycle(state, acc)
    jax.block_until_ready((state, acc))
    dt = time.perf_counter() - t0

    rate = BATCH * ACCUM_BATCHES * CYCLES / dt
    print(
        json.dumps(
            {
                "metric": "flow_records_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": "records/s",
                "vs_baseline": round(rate / TARGET, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
