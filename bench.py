#!/usr/bin/env python
"""Benchmark: flow-records/sec/chip through the L4 rollup hot path.

Measures the steady-state jit ingest step (fanout → fingerprint →
sort/segment stash merge) on the attached accelerator, replaying the
BASELINE config-1 workload shape: synthetic accumulated-flow batches over
10k unique 5-tuples at 1s windows.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is against the north-star target of 50M records/sec/chip
(BASELINE.json; the reference publishes no absolute numbers — SURVEY §6).
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from deepflow_tpu.aggregator.fanout import FanoutConfig
from deepflow_tpu.aggregator.pipeline import make_ingest_step
from deepflow_tpu.aggregator.stash import stash_init
from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
from deepflow_tpu.ingest.replay import SyntheticFlowGen

TARGET = 50e6  # records/sec/chip north star

BATCH = 1 << 14  # flows per step (→ 4x doc rows)
CAPACITY = 1 << 16
WARMUP = 3
ITERS = 20


def main():
    gen = SyntheticFlowGen(num_tuples=10_000, seed=0)
    fb = gen.flow_batch(BATCH, 1_700_000_000)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    meters = jnp.asarray(fb.meters)
    valid = jnp.asarray(fb.valid)

    step_fn = make_ingest_step(FanoutConfig(), interval=1)
    step = jax.jit(step_fn, donate_argnums=(0,))

    state = stash_init(CAPACITY, TAG_SCHEMA, FLOW_METER)
    for _ in range(WARMUP):
        state = step(state, tags, meters, valid)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state = step(state, tags, meters, valid)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    rate = BATCH * ITERS / dt
    print(
        json.dumps(
            {
                "metric": "flow_records_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": "records/s",
                "vs_baseline": round(rate / TARGET, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
