#!/usr/bin/env python
"""Benchmark: flow-records/sec/chip through the L4 rollup hot path.

Measures the steady-state ingest cycle on the attached accelerator,
replaying the BASELINE config-1 workload shape: synthetic
accumulated-flow batches over 10k unique 5-tuples at 1s windows.

The cycle is the production cadence (aggregator/pipeline.py): per batch
one `append` (batch-local groupby pre-reduce → fanout → packed-word
fingerprint → accumulator write), and every ACCUM_BATCHES batches one
`fold` (the amortized sort+segment reduce of [stash + accumulator]
rows). The pre-reduce (PERF.md §7) collapses each batch to its unique
raw keys BEFORE the 4-lane doc fanout — exact for any workload, and the
reason fold rows stop scaling with the dup factor. Reported records/sec
includes the full amortized cost of aggregation, not just the append.

Timing uses an explicit host fetch as the sync point: on the remote
accelerator tunnel `block_until_ready` returns before execution
completes (PERF.md §6), so the loop chains state through K cycles and
subtracts one measured fetch latency.

Wedge-proofing (r5 verdict #1): compiling batch shapes past the
known-good envelope has twice wedged the accelerator tunnel for the
rest of the session (PERF.md §5, §9c — a dead `jax.devices()` hang, not
an exception). The shape gate below encodes that rule in code: BATCH >
MAX_SAFE_BATCH is refused (rc=2, parseable record) unless BENCH_FORCE=1
is set explicitly. Backend failures (tunnel dead, backend init error)
emit a PARTIAL record — same schema, value 0, an `error` field — and
exit 0, so the driver always gets one parseable JSON line instead of a
raw traceback.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is against the north-star target of 50M records/sec/chip
(BASELINE.json; the reference publishes no absolute numbers — SURVEY §6).
"""

from __future__ import annotations

import json
import os
import sys
import time

TARGET = 50e6  # records/sec/chip north star

# Measured-safe shapes (PERF.md §7/§9, on-chip): compile+first ~105 s at
# these sizes. The fold sorts CAPACITY + ACCUM_BATCHES×4×UNIQUE_CAP rows
# (262k here); the appends sort BATCH raw rows. UNIQUE_CAP bounds
# per-batch unique keys (3x headroom over the 10k-tuple workload);
# overflow is shed and counted, never silent.
BATCH = int(os.environ.get("BENCH_BATCH", 1 << 21))  # flows per step
CAPACITY = int(os.environ.get("BENCH_CAPACITY", 1 << 16))  # stash segments
ACCUM_BATCHES = int(os.environ.get("BENCH_ACCUM_BATCHES", 2))
UNIQUE_CAP = int(os.environ.get("BENCH_UNIQUE_CAP", 1 << 15))
WARMUP_CYCLES = 1
CYCLES = int(os.environ.get("BENCH_CYCLES", 8))

# Known-good compiled-shape envelope (PERF.md §5, §9c): a 4M-batch probe
# wedged the axon tunnel for the whole session, twice. Encoded here so
# the rule survives operator turnover; BENCH_FORCE=1 overrides.
MAX_SAFE_BATCH = 1 << 21


def _record(value: float, **extra) -> str:
    return json.dumps(
        {
            "metric": "flow_records_per_sec_per_chip",
            "value": round(value, 1),
            "unit": "records/s",
            "vs_baseline": round(value / TARGET, 4),
            **extra,
        }
    )


def _run() -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepflow_tpu.aggregator.fanout import FANOUT_LANES, FanoutConfig
    from deepflow_tpu.aggregator.pipeline import make_ingest_step
    from deepflow_tpu.aggregator.stash import accum_init, stash_init
    from deepflow_tpu.datamodel.schema import FLOW_METER, TAG_SCHEMA
    from deepflow_tpu.ingest.replay import SyntheticFlowGen

    gen = SyntheticFlowGen(num_tuples=10_000, seed=0)
    fb = gen.flow_batch(BATCH, 1_700_000_000)
    tags = {k: jnp.asarray(v) for k, v in fb.tags.items()}
    meters = jnp.asarray(fb.meters)
    valid = jnp.asarray(fb.valid)

    append_fn, fold_fn = make_ingest_step(
        FanoutConfig(), interval=1, batch_unique_cap=UNIQUE_CAP or None
    )
    append = jax.jit(append_fn, donate_argnums=(0, 1))
    fold = jax.jit(fold_fn, donate_argnums=(0, 1))

    stride = FANOUT_LANES * (UNIQUE_CAP or BATCH)
    state = stash_init(CAPACITY, TAG_SCHEMA, FLOW_METER)
    acc = accum_init(ACCUM_BATCHES * stride, TAG_SCHEMA, FLOW_METER)

    def cycle(state, acc):
        for k in range(ACCUM_BATCHES):
            state, acc = append(state, acc, jnp.int32(k * stride), tags, meters, valid)
        return fold(state, acc)

    for _ in range(WARMUP_CYCLES):
        state, acc = cycle(state, acc)
    _ = np.asarray(state.slot[:1])  # true host sync (compile + warmup done)

    t0 = time.perf_counter()
    _ = np.asarray(state.slot[:1])
    fetch_base = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(CYCLES):
        state, acc = cycle(state, acc)
    _ = np.asarray(state.slot[:1])
    dt = time.perf_counter() - t0 - fetch_base

    return BATCH * ACCUM_BATCHES * CYCLES / dt


def main() -> int:
    # Shape gate FIRST — before any jax import can touch the backend.
    if BATCH > MAX_SAFE_BATCH and os.environ.get("BENCH_FORCE") != "1":
        print(
            _record(
                0.0,
                partial=True,
                error=(
                    f"BENCH_BATCH={BATCH} exceeds the known-good compiled-shape "
                    f"envelope (≤{MAX_SAFE_BATCH}; PERF.md §5/§9c tunnel wedge); "
                    "set BENCH_FORCE=1 to override"
                ),
            )
        )
        return 2

    try:
        rate = _run()
    except Exception as e:  # backend init/compile/runtime failure
        print(
            _record(
                0.0,
                partial=True,
                error=f"{type(e).__name__}: {e}",
            )
        )
        return 0
    print(_record(rate))
    return 0


if __name__ == "__main__":
    sys.exit(main())
